"""ObjectRef: a future-like handle to a remote object.

Capability parity with the reference ObjectRef (python/ray/includes/object_ref.pxi):
holds the object id + owner address, participates in distributed refcounting via
callbacks registered by the core worker, and is awaitable.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Optional

from ray_tpu._private.ids import ObjectID

# Set by the core worker when connected; used for __del__ deref and await.
_ref_removed_callback: Optional[Callable[["ObjectRef"], None]] = None
_ref_added_callback: Optional[Callable[["ObjectRef"], None]] = None
_get_callback: Optional[Callable[["ObjectRef", Optional[float]], Any]] = None
_async_get_callback = None


def _set_core_worker_hooks(on_added, on_removed, get_fn, async_get_fn):
    global _ref_added_callback, _ref_removed_callback, _get_callback, _async_get_callback
    _ref_added_callback = on_added
    _ref_removed_callback = on_removed
    _get_callback = get_fn
    _async_get_callback = async_get_fn


class ObjectRef:
    __slots__ = ("id", "owner_address", "_skip_refcount", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: str = "",
                 skip_refcount: bool = False):
        self.id = object_id
        self.owner_address = owner_address
        self._skip_refcount = skip_refcount
        if not skip_refcount and _ref_added_callback is not None:
            _ref_added_callback(self)

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def task_id(self):
        return self.id.task_id()

    def job_id(self):
        return self.id.job_id()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __del__(self):
        if not self._skip_refcount and _ref_removed_callback is not None:
            try:
                _ref_removed_callback(self)
            except Exception:
                pass

    def future(self) -> asyncio.Future:
        if _async_get_callback is None:
            raise RuntimeError("ray_tpu not initialized")
        return asyncio.ensure_future(_async_get_callback(self))

    def __await__(self):
        if _async_get_callback is None:
            raise RuntimeError("ray_tpu not initialized")
        return _async_get_callback(self).__await__()

    def __reduce__(self):
        # Serialization of a bare ref outside the serializer context still
        # round-trips, but does not register a borrower.
        return (ObjectRef, (self.id, self.owner_address, True))
