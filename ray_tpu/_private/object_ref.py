"""ObjectRef: a future-like handle to a remote object.

Capability parity with the reference ObjectRef (python/ray/includes/object_ref.pxi):
holds the object id + owner address, participates in distributed refcounting via
callbacks registered by the core worker, and is awaitable.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Optional

from ray_tpu._private.ids import ObjectID

# Set by the core worker when connected; used for __del__ deref and await.
_ref_removed_callback: Optional[Callable[["ObjectRef"], None]] = None
_ref_added_callback: Optional[Callable[["ObjectRef"], None]] = None
_get_callback: Optional[Callable[["ObjectRef", Optional[float]], Any]] = None
_async_get_callback = None


def _set_core_worker_hooks(on_added, on_removed, get_fn, async_get_fn):
    global _ref_added_callback, _ref_removed_callback, _get_callback, _async_get_callback
    _ref_added_callback = on_added
    _ref_removed_callback = on_removed
    _get_callback = get_fn
    _async_get_callback = async_get_fn


class ObjectRef:
    __slots__ = ("id", "owner_address", "_skip_refcount", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: str = "",
                 skip_refcount: bool = False):
        self.id = object_id
        self.owner_address = owner_address
        self._skip_refcount = skip_refcount
        if not skip_refcount and _ref_added_callback is not None:
            _ref_added_callback(self)

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def task_id(self):
        return self.id.task_id()

    def job_id(self):
        return self.id.job_id()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __del__(self):
        if not self._skip_refcount and _ref_removed_callback is not None:
            try:
                _ref_removed_callback(self)
            except Exception:
                pass

    def future(self) -> asyncio.Future:
        if _async_get_callback is None:
            raise RuntimeError("ray_tpu not initialized")
        return asyncio.ensure_future(_async_get_callback(self))

    def __await__(self):
        if _async_get_callback is None:
            raise RuntimeError("ray_tpu not initialized")
        return _async_get_callback(self).__await__()

    def __reduce__(self):
        # Serialization of a bare ref outside the serializer context still
        # round-trips, but does not register a borrower.
        return (ObjectRef, (self.id, self.owner_address, True))


class ObjectRefGenerator:
    """Iterator over a streaming task's return refs
    (num_returns="streaming"; reference: python/ray/_raylet.pyx
    ObjectRefGenerator over task_manager.h ObjectRefStream).

    Yields ObjectRefs as the executing generator produces items; works as a
    sync iterator from user threads and an async iterator inside async
    actors.
    """

    def __init__(self, task_id, core):
        self._task_id = task_id
        self._core = core
        self._cursor = 0
        self._exhausted = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        from ray_tpu._private import worker_api
        ref = worker_api._call_on_core_loop(
            self._core, self._core.generator_next(self._task_id,
                                                  self._cursor), None)
        if ref is None:
            self._exhausted = True
            raise StopIteration
        self._cursor += 1
        return ref

    def try_next(self):
        """Non-blocking __next__: the next ObjectRef if an item is ready,
        None when the producer hasn't yielded it yet; StopIteration when
        the stream is exhausted."""
        if self._exhausted:
            raise StopIteration
        from ray_tpu._private import worker_api
        kind, ref = worker_api._call_on_core_loop(
            self._core, self._core.generator_try_next(self._task_id,
                                                      self._cursor), 30)
        if kind == "done":
            self._exhausted = True
            raise StopIteration
        if kind == "pending":
            return None
        self._cursor += 1
        return ref

    def __aiter__(self):
        return self

    async def __anext__(self):
        if self._exhausted:
            raise StopAsyncIteration
        ref = await self._core.generator_next(self._task_id, self._cursor)
        if ref is None:
            self._exhausted = True
            raise StopAsyncIteration
        self._cursor += 1
        return ref

    def __del__(self):
        # Abandoned mid-stream: free owner-side stream state + unconsumed
        # items so long-lived drivers don't leak (the stream entry is gone
        # already if iteration completed).
        if self._exhausted:
            return
        try:
            core = self._core
            core.loop.call_soon_threadsafe(core.release_generator,
                                           self._task_id, self._cursor)
        except Exception:
            pass
