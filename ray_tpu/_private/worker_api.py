"""Driver-side global state + the init/get/put/wait API core.

Reference parity: python/ray/_private/worker.py (ray.init :1219, get :2547,
put :2679, wait :2744, shutdown :1796, get_actor :2890).
"""

from __future__ import annotations

import asyncio
import atexit
import logging
import threading
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu import exceptions as exc
from ray_tpu._private.config import Config, set_config
from ray_tpu._private.core_worker import CoreWorker
from ray_tpu._private.node import HeadNode, detect_node_resources
from ray_tpu._private.object_ref import ObjectRef

logger = logging.getLogger(__name__)


class _GlobalState:
    def __init__(self):
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.loop_thread: Optional[threading.Thread] = None
        self.head: Optional[HeadNode] = None
        self.core: Optional[CoreWorker] = None
        self.initialized = False
        self.namespace = ""
        self.gcs_address = ""
        self.exported_functions: Dict[str, bool] = {}
        # Job-level default runtime env (init(runtime_env=...)); merged
        # under per-task/actor envs by resolve_runtime_env.
        self.job_runtime_env: Optional[dict] = None
        # Ray-client mode (init(address="ray_tpu://...")): every API call
        # proxies through this context instead of a local CoreWorker.
        self.client = None

    def run(self, coro, timeout: Optional[float] = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)


_state = _GlobalState()


def _ensure_loop():
    if _state.loop is not None:
        return
    ready = threading.Event()

    def _run():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        _state.loop = loop
        ready.set()
        loop.run_forever()

    t = threading.Thread(target=_run, daemon=True, name="ray_tpu-loop")
    t.start()
    _state.loop_thread = t
    ready.wait(10)


def is_initialized() -> bool:
    return _state.initialized


def get_core() -> CoreWorker:
    # Worker-process context: the executing CoreWorker registers itself here
    # so user code inside tasks can call the public API.
    if _worker_core.core is not None:
        return _worker_core.core
    if not _state.initialized:
        init()
    return _state.core


def peek_core() -> Optional[CoreWorker]:
    """The live CoreWorker, or None — NEVER auto-initializes. For
    observability paths (span export, serve request events) that must
    degrade to buffering instead of spinning up a cluster as a side
    effect."""
    if _worker_core.core is not None:
        return _worker_core.core
    return _state.core if _state.initialized else None


class _WorkerCore:
    """Set inside worker processes (see worker_main) for API reentrancy."""
    def __init__(self):
        self.core: Optional[CoreWorker] = None


_worker_core = _WorkerCore()


def init(address: Optional[str] = None, *,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         labels: Optional[Dict[str, str]] = None,
         object_store_memory: Optional[int] = None,
         namespace: str = "",
         runtime_env: Optional[dict] = None,
         system_config: Optional[dict] = None,
         ignore_reinit_error: bool = True,
         log_level: int = logging.WARNING):
    """Start (or connect to) a cluster and connect this driver."""
    if _state.initialized:
        if ignore_reinit_error:
            return _state
        raise RuntimeError("ray_tpu already initialized")
    if isinstance(address, str) and (address.startswith("ray_tpu://")
                                     or address.startswith("ray://")):
        # Client mode (reference: ray.init("ray://...")): the process
        # never joins the cluster network; the whole API proxies through
        # the head's ClientServer. runtime_env packages are zipped locally
        # and shipped with the first submission that references them.
        from ray_tpu.util.client import ClientContext
        endpoint = address.split("://", 1)[1]
        _state.client = ClientContext(endpoint, namespace=namespace,
                                      runtime_env=runtime_env)
        _state.namespace = namespace
        _state.initialized = True
        atexit.register(shutdown)
        return _state
    from ray_tpu._private import runtime_env as _re
    _state.job_runtime_env = _re.validate(runtime_env)
    if address in (None, "auto"):
        # Job entrypoints / CLI children inherit the cluster address
        # (reference: RAY_ADDRESS handling in ray.init).
        import os as _os
        address = _os.environ.get("RAY_TPU_ADDRESS") or None
    logging.basicConfig(level=log_level)
    config = Config.load(system_config)
    set_config(config)
    _ensure_loop()
    _state.namespace = namespace

    async def _boot():
        if address is None:
            res = detect_node_resources(num_cpus, num_tpus, resources, config)
            head = HeadNode(config, resources=res, labels=labels,
                            object_store_memory=object_store_memory)
            gcs_address = await head.start()
            raylet_address = head.raylet.address
            _state.head = head
        else:
            gcs_address = address
            from ray_tpu._private import rpc
            conn = await rpc.connect(gcs_address)
            nodes = await conn.request("get_all_nodes", {})
            await conn.close()
            alive = [n for n in nodes if n.alive]
            if not alive:
                raise exc.RayTpuSystemError("no alive nodes in cluster")
            heads = [n for n in alive if n.is_head]
            raylet_address = (heads[0] if heads else alive[0]).address
        from ray_tpu._private import rpc
        conn = await rpc.connect(gcs_address)
        job_id = await conn.request("register_job",
                                    {"driver_address": "", "entrypoint": ""})
        await conn.close()
        core = CoreWorker("driver", gcs_address, raylet_address, config,
                          job_id=job_id)
        await core.start_async()
        _state.core = core
        _state.gcs_address = gcs_address
        return gcs_address

    _state.run(_boot(), timeout=60)
    _state.initialized = True
    atexit.register(shutdown)
    return _state


def client_mode():
    return _state.client


def shutdown():
    if not _state.initialized:
        return
    if _state.client is not None:
        try:
            _state.client.disconnect()
        except Exception:
            pass
        _state.client = None
        _state.initialized = False
        return
    try:
        if _state.core is not None:
            _state.run(_state.core.shutdown_async(), timeout=10)
    except Exception:
        pass
    try:
        if _state.head is not None:
            _state.run(_state.head.stop(), timeout=10)
    except Exception:
        pass
    _state.core = None
    _state.head = None
    _state.initialized = False
    _state.exported_functions.clear()
    _state.job_runtime_env = None


def resolve_runtime_env(env: Optional[dict]) -> Optional[dict]:
    """Merge a per-task/actor env over the job default and validate."""
    from ray_tpu._private import runtime_env as _re
    merged = _re.merge(_state.job_runtime_env, _re.validate(env))
    return merged


def put(value: Any) -> ObjectRef:
    if _state.client is not None:
        return _state.client.put(value)
    core = get_core()
    # put_sync is thread-safe: inline-size values never cross threads; large
    # values only hop to the loop for the store RPCs.
    return core.put_sync(value)


def get(refs, timeout: Optional[float] = None):
    if _state.client is not None:
        return _state.client.get(refs, timeout)
    core = get_core()
    if isinstance(refs, (list, tuple)):
        bad = [r for r in refs if not isinstance(r, ObjectRef)]
        if bad:
            raise TypeError(
                f"get() expects ObjectRefs; got {type(bad[0]).__name__}")
        refs = list(refs)
    elif not isinstance(refs, ObjectRef):
        raise TypeError(
            f"get() expects an ObjectRef or a list of them; got "
            f"{type(refs).__name__}")
    coro = core.get_async(refs, timeout)
    return _call_on_core_loop(core, coro, timeout)


def get_local(ref: ObjectRef, timeout: Optional[float] = None):
    """Node-local object-plane get: `(value,)` when this node's store
    holds the object (pinned zero-copy view), None when it does not.
    Never crosses the network — callers fall back to `get()` for the
    cross-node transfer path."""
    if _state.client is not None:
        return None  # client mode has no node-local store
    core = get_core()
    if not isinstance(ref, ObjectRef):
        raise TypeError(f"get_local() expects an ObjectRef; got "
                        f"{type(ref).__name__}")
    return _call_on_core_loop(core, core.get_local_async(ref, timeout),
                              timeout)


def wait(refs: List[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if _state.client is not None:
        return _state.client.wait(list(refs), num_returns=num_returns,
                                  timeout=timeout)
    core = get_core()
    refs = list(refs)
    if any(not isinstance(r, ObjectRef) for r in refs):
        raise TypeError("wait() expects a list of ObjectRefs")
    coro = core.wait_async(refs, num_returns, timeout, fetch_local)
    return _call_on_core_loop(core, coro, None)


def _on_core_loop(core: CoreWorker) -> bool:
    """True when the caller is executing on the core event loop thread
    (async actor methods, serve replicas/controller)."""
    try:
        return asyncio.get_running_loop() is core.loop
    except RuntimeError:
        return False


def _call_on_core_loop(core: CoreWorker, coro, timeout):
    """Run coro on the core loop from whatever thread we're on."""
    if _on_core_loop(core):
        coro.close()
        raise RuntimeError(
            "blocking API called from the core event loop; use await/async "
            "variants inside async actors")
    fut = asyncio.run_coroutine_threadsafe(coro, core.loop)
    return fut.result(None if timeout is None else timeout + 10)


def kill(actor, *, no_restart: bool = True):
    if _state.client is not None:
        return _state.client.kill(actor, no_restart)
    from ray_tpu.actor import ActorHandle
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    core = get_core()
    if _on_core_loop(core):
        # Async-actor context: fire and forget (kill is idempotent).
        asyncio.ensure_future(core.kill_actor(actor._actor_id, no_restart))
        return
    _call_on_core_loop(core, core.kill_actor(actor._actor_id, no_restart), 10)


def cancel(ref: ObjectRef, *, force: bool = False):
    if _state.client is not None:
        return _state.client.cancel(ref, force)
    core = get_core()
    _call_on_core_loop(core, core.cancel_task(ref, force), 10)


def get_actor(name: str, namespace: Optional[str] = None):
    if _state.client is not None:
        return _state.client.get_actor(name, namespace)
    from ray_tpu.actor import ActorHandle
    core = get_core()
    ns = namespace if namespace is not None else _state.namespace
    info = _call_on_core_loop(core, core.get_named_actor(name, ns), 10)
    return ActorHandle._from_actor_info(info)


def nodes() -> List[dict]:
    if _state.client is not None:
        return _state.client.nodes()
    core = get_core()
    infos = _call_on_core_loop(core, core.gcs.request("get_all_nodes", {}), 10)
    return [{
        "NodeID": n.node_id.hex(), "Alive": n.alive, "Address": n.address,
        "Resources": n.resources_total, "Labels": n.labels,
        "IsHead": n.is_head, "Draining": getattr(n, "draining", False),
        "SliceId": getattr(n, "slice_id", ""),
    } for n in infos]


async def prestart_workers_async(core, count: int,
                                 runtime_env: Optional[dict] = None) -> int:
    """Core-loop half of prestart_workers — the ONE place that prepares
    the env and shapes the hint RPC (the serve controller calls this
    directly; keep the payload in sync with raylet rpc_prestart_workers
    by editing here, not at call sites)."""
    env = resolve_runtime_env(runtime_env)
    env_hash = ""
    if env:
        if env.get("container"):
            # Container workers need dedicated spawns (WarmPools.pop is
            # exact-only for them — a generic process can never enter
            # the container retroactively): a hint would fork generic
            # workers no container create can use, and pin the fresh
            # pool floor doing it. Same skip the GCS's own hint path
            # (_send_prestart_hints) applies.
            return 0
        # Same packaging + hash stamping the actor spec will get, so
        # the hint keys the SAME pool the creates will ask for (and
        # the package upload itself is pre-warmed).
        prepared = await core.prepare_runtime_env(dict(env))
        env_hash = prepared.get("_hash", "")
    return await core.gcs.request(
        "prestart_workers", {"count": int(count), "env_hash": env_hash})


def prestart_workers(count: int, runtime_env: Optional[dict] = None) -> int:
    """Warm the cluster's worker pools ahead of a launch storm: `count`
    actor/task creations for `runtime_env` are about to be submitted.
    The GCS fans the hint across schedulable raylets (env-keyed pool
    floors + immediate multi-spawn through the forkserver), so the storm
    finds forked workers instead of paying cold process boots. Best
    effort; returns the number of nodes hinted."""
    core = get_core()
    return _call_on_core_loop(
        core, prestart_workers_async(core, count, runtime_env), 30)


def drain_events() -> List[dict]:
    """Drain/preemption notices observed by this process's core worker
    ({"time", "node_id", "address", "deadline"} per event). Train uses
    this to classify gang failures as planned (uncharged) losses."""
    core = _worker_core.core or _state.core
    return list(core.drain_events) if core is not None else []


def add_drain_event_listener(cb) -> bool:
    """Register a push wakeup fired (from the core loop) whenever a
    drain/preemption notice lands in this process's drain-event log.
    Returns False when no core worker is connected — the caller should
    fall back to polling drain_events(). The callback must be cheap and
    thread-agnostic (typically threading.Event.set)."""
    core = _worker_core.core or _state.core
    if core is None:
        return False
    core.drain_listeners.append(cb)
    return True


def remove_drain_event_listener(cb) -> None:
    core = _worker_core.core or _state.core
    if core is not None:
        try:
            core.drain_listeners.remove(cb)
        except ValueError:
            pass


def local_node_draining() -> bool:
    """True inside a process whose hosting node received a drain notice
    (spot reclaim / downscale). The save-on-preempt hook: a training loop
    should checkpoint now — this host is going away."""
    core = _worker_core.core or _state.core
    return bool(core is not None and core.local_node_draining)


def cluster_resources() -> Dict[str, float]:
    if _state.client is not None:
        return _state.client.cluster_resources()
    core = get_core()
    view = _call_on_core_loop(core,
                              core.gcs.request("get_cluster_resources", {}), 10)
    out: Dict[str, float] = {}
    for info in view.values():
        if not info["alive"]:
            continue
        for k, v in info["total"].items():
            out[k] = out.get(k, 0) + v
    return out


def available_resources() -> Dict[str, float]:
    core = get_core()
    view = _call_on_core_loop(core,
                              core.gcs.request("get_cluster_resources", {}), 10)
    out: Dict[str, float] = {}
    for info in view.values():
        if not info["alive"]:
            continue
        for k, v in info["available"].items():
            out[k] = out.get(k, 0) + v
    return out


def internal_kv_put(key: bytes, value: bytes, namespace: str = "kv",
                    overwrite: bool = True) -> bool:
    """Cluster-wide KV (reference: ray.experimental.internal_kv)."""
    core = get_core()
    return _call_on_core_loop(core, core.gcs.request("kv_put", {
        "namespace": namespace, "key": key, "value": value,
        "overwrite": overwrite}), 30)


def internal_kv_get(key: bytes, namespace: str = "kv") -> Optional[bytes]:
    core = get_core()
    return _call_on_core_loop(core, core.gcs.request("kv_get", {
        "namespace": namespace, "key": key}), 30)


def internal_kv_del(key: bytes, namespace: str = "kv") -> bool:
    core = get_core()
    return _call_on_core_loop(core, core.gcs.request("kv_del", {
        "namespace": namespace, "key": key}), 30)


def internal_kv_keys(prefix: bytes = b"", namespace: str = "kv") -> List[bytes]:
    core = get_core()
    return _call_on_core_loop(core, core.gcs.request("kv_keys", {
        "namespace": namespace, "prefix": prefix}), 30)


# Awaitable internal-KV variants for ON-LOOP callers (async actors — the
# serve controller's write-ahead store is the main one): the sync
# wrappers above block on the core loop and would deadlock there.

async def internal_kv_put_async(core, key: bytes, value: bytes,
                                namespace: str = "kv",
                                overwrite: bool = True) -> bool:
    return await core.gcs.request("kv_put", {
        "namespace": namespace, "key": key, "value": value,
        "overwrite": overwrite})


async def internal_kv_get_async(core, key: bytes,
                                namespace: str = "kv") -> Optional[bytes]:
    return await core.gcs.request("kv_get", {
        "namespace": namespace, "key": key})


async def internal_kv_del_async(core, key: bytes,
                                namespace: str = "kv") -> bool:
    return await core.gcs.request("kv_del", {
        "namespace": namespace, "key": key})


async def internal_kv_keys_async(core, prefix: bytes = b"",
                                 namespace: str = "kv") -> List[bytes]:
    return await core.gcs.request("kv_keys", {
        "namespace": namespace, "prefix": prefix})


def timeline(job_id=None) -> List[dict]:
    """Chrome-trace-format task timeline (reference: ray.timeline).

    Flight-recorder upgrade: besides one "X" slice per completed task,
    the export carries per-phase sub-slices (args_resolve / exec /
    result_put on the executing worker's lane, submit->dispatch on the
    owner's) and `ph:"s"/"f"` flow events that connect a submission on
    the driver to its execution on the worker across pids — load the
    file in chrome://tracing or Perfetto to follow a task hop by hop."""
    from ray_tpu._private import flightrec
    core = get_core()
    events = _call_on_core_loop(
        core, core.gcs.request("get_task_events",
                               {"job_id": job_id, "limit": 100000}), 30)
    return flightrec.build_trace(events)
