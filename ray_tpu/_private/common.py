"""Shared control-plane datatypes (TaskSpec, ActorSpec, resources, etc.).

Equivalent of the reference's protobuf common.proto (TaskSpec, Address) —
plain dataclasses since the RPC layer is pickle-based.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import (ActorID, JobID, NodeID, ObjectID,
                                  PlacementGroupID, TaskID, WorkerID)

# Argument kinds
ARG_INLINE = 0   # serialized bytes shipped in the task spec
ARG_REF = 1      # ObjectID; executor resolves before running


@dataclass
class TaskArg:
    kind: int
    data: bytes = b""                       # for ARG_INLINE: serialized value
    object_id: Optional[ObjectID] = None    # for ARG_REF
    owner_address: str = ""

    def __reduce__(self):
        # Positional tuple: every task/actor call pickles specs, so skip the
        # dataclass default of shipping __dict__ with field-name strings.
        return (TaskArg, (self.kind, self.data, self.object_id,
                          self.owner_address))


@dataclass
class SchedulingStrategy:
    """DEFAULT | SPREAD | NODE_AFFINITY | NODE_LABEL | PLACEMENT_GROUP"""
    kind: str = "DEFAULT"
    node_id: Optional[NodeID] = None
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False
    # NODE_LABEL: label -> list of allowed values (In semantics)
    labels_hard: Optional[Dict[str, list]] = None
    labels_soft: Optional[Dict[str, list]] = None

    def __reduce__(self):
        return (SchedulingStrategy,
                (self.kind, self.node_id, self.soft,
                 self.placement_group_id, self.bundle_index,
                 self.capture_child_tasks, self.labels_hard,
                 self.labels_soft))


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    name: str = ""
    # Function is exported to the GCS function table under this key.
    function_id: str = ""
    args: List[TaskArg] = field(default_factory=list)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 0
    retry_exceptions: bool = False
    owner_address: str = ""
    owner_worker_id: Optional[WorkerID] = None
    # Actor-task fields
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    seq_no: int = 0
    # Actor-creation fields
    is_actor_creation: bool = False
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    is_async_actor: bool = False
    actor_name: str = ""
    namespace: str = ""
    # Real runtime environment (env_vars/working_dir/py_modules, with a
    # precomputed "_hash"); see _private/runtime_env.py.
    runtime_env: Optional[dict] = None
    # Generator tasks
    is_generator: bool = False
    # Keyword-argument names for the trailing args (executor rebuilds kwargs)
    kwarg_names: Tuple[str, ...] = ()
    # Actor lifetime ("" | "detached")
    lifetime: str = ""
    # Concurrency groups (reference: concurrency_group_manager.h):
    # declared on the actor-creation spec {name: max_concurrency}; actor
    # tasks carry the group they execute in ("" = default group).
    concurrency_groups: Optional[Dict[str, int]] = None
    concurrency_group: str = ""
    # Out-of-order actor execution: receiver skips per-caller seq gating
    # (reference: out_of_order_actor_scheduling_queue.cc).
    execute_out_of_order: bool = False
    # @method-decorator defaults per method name (num_returns,
    # concurrency_group); persisted so get_actor handles honor them.
    method_options: Optional[Dict[str, dict]] = None
    # Tracing context (trace_id, parent_span_id) — reference:
    # tracing_helper.py _DictPropagator inside task specs.
    trace_ctx: Optional[Tuple[str, str]] = None

    def env_hash(self) -> str:
        return (self.runtime_env or {}).get("_hash", "")

    def scheduling_class(self) -> Tuple:
        """Tasks with the same class can reuse worker leases."""
        tmpl = self.__dict__.get("_tmpl")
        if tmpl is not None:
            return tmpl.sched_class

        def freeze(constraint):
            if not constraint:
                return None
            return tuple(sorted((k, tuple(v))
                                for k, v in constraint.items()))
        return (
            tuple(sorted(self.resources.items())),
            self.scheduling.kind,
            self.scheduling.node_id,
            self.scheduling.placement_group_id,
            self.scheduling.bundle_index,
            # label constraints are part of the class: a lease on a node
            # matching one constraint must not serve a different one
            freeze(self.scheduling.labels_hard),
            freeze(self.scheduling.labels_soft),
            self.env_hash(),
        )

    def __reduce__(self):
        # Hot path: pickled once per task/actor call. Wire-compact tuple:
        # IDs travel as raw bytes and TaskArg/SchedulingStrategy flatten to
        # tuples, skipping per-object pickle class dispatch (measured 17us
        # -> 9us per spec round trip, and 362 -> 190 wire bytes).
        return (_unwire_task_spec, ((
            self.task_id.binary(), self.job_id.binary(), self.name,
            self.function_id,
            _wire_args(self.args),
            self.num_returns, self.resources, _wire_sched(self.scheduling),
            self.max_retries, self.retry_exceptions, self.owner_address,
            self.owner_worker_id.binary()
            if self.owner_worker_id is not None else None,
            self.actor_id.binary() if self.actor_id is not None else None,
            self.method_name, self.seq_no, self.is_actor_creation,
            self.max_restarts, self.max_task_retries, self.max_concurrency,
            self.is_async_actor, self.actor_name, self.namespace,
            self.runtime_env, self.is_generator, self.kwarg_names,
            self.lifetime, self.concurrency_groups, self.concurrency_group,
            self.execute_out_of_order, self.method_options,
            self.trace_ctx),))


def _wire_sched(s: SchedulingStrategy):
    if (s.kind == "DEFAULT" and s.node_id is None and not s.soft
            and s.placement_group_id is None and s.bundle_index == -1
            and not s.capture_child_tasks and not s.labels_hard
            and not s.labels_soft):
        return None  # the overwhelmingly common default strategy
    return (s.kind,
            s.node_id.binary() if s.node_id is not None else None,
            s.soft,
            s.placement_group_id.binary()
            if s.placement_group_id is not None else None,
            s.bundle_index, s.capture_child_tasks,
            s.labels_hard, s.labels_soft)


def _unwire_sched(sched) -> SchedulingStrategy:
    if sched is None:
        return SchedulingStrategy()
    (kind, node_id, soft, pg_id, bundle_index, capture, hard,
     soft_labels) = sched
    return SchedulingStrategy(
        kind, NodeID(node_id) if node_id is not None else None, soft,
        PlacementGroupID(pg_id) if pg_id is not None else None,
        bundle_index, capture, hard, soft_labels)


def _wire_args(args) -> list:
    return [(a.kind, a.data,
             a.object_id.binary() if a.object_id is not None else None,
             a.owner_address) for a in args]


def _unwire_args(args) -> list:
    return [TaskArg(k, d, ObjectID(o) if o is not None else None, oa)
            for k, d, o, oa in args]


def _unwire_task_spec(w: tuple) -> "TaskSpec":
    """Rebuild a TaskSpec from its wire tuple (see TaskSpec.__reduce__)."""
    (tid, jid, name, fid, args, num_returns, resources, sched, max_retries,
     retry_exceptions, owner_address, owner_wid, actor_id, method_name,
     seq_no, is_actor_creation, max_restarts, max_task_retries,
     max_concurrency, is_async_actor, actor_name, namespace, runtime_env,
     is_generator, kwarg_names, lifetime, concurrency_groups,
     concurrency_group, execute_out_of_order, method_options, trace_ctx) = w
    return TaskSpec(
        TaskID(tid), JobID(jid), name, fid, _unwire_args(args),
        num_returns, resources, _unwire_sched(sched), max_retries,
        retry_exceptions,
        owner_address, WorkerID(owner_wid) if owner_wid is not None else None,
        ActorID(actor_id) if actor_id is not None else None, method_name,
        seq_no, is_actor_creation, max_restarts, max_task_retries,
        max_concurrency, is_async_actor, actor_name, namespace, runtime_env,
        is_generator, kwarg_names, lifetime, concurrency_groups,
        concurrency_group, execute_out_of_order, method_options, trace_ctx)


# ---------------------------------------------------------------------------
# Task-spec templates: the caller-side hot path for repeated call sites.
#
# A steady-state `.remote()` call repeats every spec field except the task
# id, the argument payload, and (for actor calls) the sequence number. A
# template pre-computes the invariant field dict, the scheduling class,
# and the wire encoding of the invariants ONCE per call site; each call
# then stamps only the per-call fields (TaskSpec.__new__ + one dict copy
# instead of a 30-kwarg dataclass construction), and a dispatch batch of
# templated specs ships the invariants once per FRAME instead of once per
# spec (see wire_spec_batch), with the executor decoding them once.
# ---------------------------------------------------------------------------

# Per-call fields excluded from the template's base dict / wire invariants.
_PER_CALL_FIELDS = ("task_id", "args", "kwarg_names", "seq_no", "trace_ctx")


class TaskSpecTemplate:
    """Invariant fields of a repeated function/actor-method call site.

    Build one from a fully-populated prototype spec (per-call fields
    ignored); `make()` stamps per-call fields onto a fresh TaskSpec.
    Templates are immutable once built — a call site whose options or
    runtime_env change must build a NEW template (the façade caches key
    off the option set, so `.options()` products never share one).
    """

    __slots__ = ("base", "sched_class", "method_name", "runtime_env",
                 "num_returns", "function_id", "token", "_wire_inv")

    def __init__(self, proto: TaskSpec, token: Any = None):
        base = dict(proto.__dict__)
        for f in _PER_CALL_FIELDS:
            base.pop(f, None)
        base.pop("_tmpl", None)
        self.base = base
        self.sched_class = proto.scheduling_class()
        self.method_name = proto.method_name
        self.runtime_env = proto.runtime_env
        self.num_returns = proto.num_returns
        self.function_id = proto.function_id
        self.token = token
        self._wire_inv = None

    def make(self, task_id: TaskID, args=(), kwarg_names=(),
             seq_no: int = 0, trace_ctx=None) -> TaskSpec:
        spec = TaskSpec.__new__(TaskSpec)
        d = dict(self.base)
        d["task_id"] = task_id
        d["args"] = args
        d["kwarg_names"] = kwarg_names
        d["seq_no"] = seq_no
        d["trace_ctx"] = trace_ctx
        d["_tmpl"] = self
        spec.__dict__ = d
        return spec

    def wire_invariants(self) -> tuple:
        """Wire tuple of the invariant fields (cached; field order matches
        _unwire_spec_batch)."""
        inv = self._wire_inv
        if inv is None:
            b = self.base
            owner_wid = b["owner_worker_id"]
            actor_id = b["actor_id"]
            inv = self._wire_inv = (
                b["job_id"].binary(), b["name"], b["function_id"],
                b["num_returns"], b["resources"],
                _wire_sched(b["scheduling"]), b["max_retries"],
                b["retry_exceptions"], b["owner_address"],
                owner_wid.binary() if owner_wid is not None else None,
                actor_id.binary() if actor_id is not None else None,
                b["method_name"], b["is_actor_creation"],
                b["max_restarts"], b["max_task_retries"],
                b["max_concurrency"], b["is_async_actor"], b["actor_name"],
                b["namespace"], b["runtime_env"], b["is_generator"],
                b["lifetime"], b["concurrency_groups"],
                b["concurrency_group"], b["execute_out_of_order"],
                b["method_options"])
        return inv


def spec_template_of(spec: TaskSpec) -> Optional[TaskSpecTemplate]:
    """The template a spec was stamped from, or None. Returns None as well
    when a template-invariant field was mutated after stamping (e.g. the
    SEQ_SKIP marker rewrite or a prepared runtime_env): such a spec must
    ship long-form."""
    tmpl = spec.__dict__.get("_tmpl")
    if tmpl is None:
        return None
    if (spec.method_name is not tmpl.method_name
            and spec.method_name != tmpl.method_name):
        return None
    if spec.runtime_env is not tmpl.runtime_env:
        return None
    return tmpl


def wire_spec_batch(specs: List[TaskSpec]):
    """Compact wire form for a dispatch batch: when every spec was stamped
    from the SAME template, the frame carries the invariants once plus one
    small per-call row per spec; otherwise the plain spec list is returned
    (legacy form — decoders handle both transparently since each form
    unpickles into a list of TaskSpecs)."""
    first = spec_template_of(specs[0])
    if first is None:
        return specs
    for s in specs:
        if spec_template_of(s) is not first:
            return specs
    return _TemplatedSpecBatch(first, specs)


class _TemplatedSpecBatch:
    """Wire-only wrapper: pickles as (invariants, per-call rows) and
    unpickles directly into the list of TaskSpecs the handlers expect."""

    __slots__ = ("tmpl", "specs")

    def __init__(self, tmpl: TaskSpecTemplate, specs: List[TaskSpec]):
        self.tmpl = tmpl
        self.specs = specs

    def __reduce__(self):
        rows = [(s.task_id.binary(), _wire_args(s.args), s.kwarg_names,
                 s.seq_no, s.trace_ctx) for s in self.specs]
        return (_unwire_spec_batch, (self.tmpl.wire_invariants(), rows))


def _unwire_spec_batch(inv: tuple, rows: list) -> List[TaskSpec]:
    """Decode the invariants ONCE, then stamp one TaskSpec per row —
    the executor-side analogue of TaskSpecTemplate.make."""
    (jid, name, fid, num_returns, resources, sched, max_retries,
     retry_exceptions, owner_address, owner_wid, actor_id, method_name,
     is_actor_creation, max_restarts, max_task_retries, max_concurrency,
     is_async_actor, actor_name, namespace, runtime_env, is_generator,
     lifetime, concurrency_groups, concurrency_group, execute_out_of_order,
     method_options) = inv
    base = {
        "job_id": JobID(jid), "name": name, "function_id": fid,
        "num_returns": num_returns, "resources": resources,
        "scheduling": _unwire_sched(sched), "max_retries": max_retries,
        "retry_exceptions": retry_exceptions,
        "owner_address": owner_address,
        "owner_worker_id":
            WorkerID(owner_wid) if owner_wid is not None else None,
        "actor_id": ActorID(actor_id) if actor_id is not None else None,
        "method_name": method_name, "is_actor_creation": is_actor_creation,
        "max_restarts": max_restarts, "max_task_retries": max_task_retries,
        "max_concurrency": max_concurrency, "is_async_actor": is_async_actor,
        "actor_name": actor_name, "namespace": namespace,
        "runtime_env": runtime_env, "is_generator": is_generator,
        "lifetime": lifetime, "concurrency_groups": concurrency_groups,
        "concurrency_group": concurrency_group,
        "execute_out_of_order": execute_out_of_order,
        "method_options": method_options,
    }
    out = []
    for tid, args, kwarg_names, seq_no, trace_ctx in rows:
        spec = TaskSpec.__new__(TaskSpec)
        d = dict(base)
        d["task_id"] = TaskID(tid)
        d["args"] = _unwire_args(args)
        d["kwarg_names"] = kwarg_names
        d["seq_no"] = seq_no
        d["trace_ctx"] = trace_ctx
        spec.__dict__ = d
        out.append(spec)
    return out


def lease_probe_spec(spec: TaskSpec) -> TaskSpec:
    """Arg-stripped shallow clone for worker-lease requests: the raylet
    reads resources/scheduling/runtime_env only, so shipping the sample
    spec's inline argument bytes in every lease RPC is pure waste."""
    if not spec.args:
        return spec
    probe = TaskSpec.__new__(TaskSpec)
    d = dict(spec.__dict__)
    d.pop("_tmpl", None)
    d["args"] = []
    d["kwarg_names"] = ()
    probe.__dict__ = d
    return probe


@dataclass
class NodeInfo:
    node_id: NodeID
    address: str                     # raylet RPC address host:port
    object_store_address: str = ""   # same daemon, store endpoints
    resources_total: Dict[str, float] = field(default_factory=dict)
    resources_available: Dict[str, float] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    is_head: bool = False
    # Two-phase removal (drain protocol): a draining node is still alive
    # (running tasks finish, objects migrate off) but receives no new
    # leases/actors/bundles; at drain_deadline it is marked dead.
    draining: bool = False
    drain_deadline: float = 0.0
    last_heartbeat: float = field(default_factory=time.time)
    # TPU topology: slice name / topology this host belongs to, if any.
    slice_id: str = ""
    # DCN locality domain (pod / cloud zone): migration off a draining
    # slice prefers replacement nodes with a MATCHING zone so the moved
    # gang / compiled DAG keeps its cross-slice traffic on-fabric.
    zone: str = ""
    hostname: str = "localhost"
    # Warm worker-pool depth per runtime-env hash ("" = fresh), synced by
    # the raylet heartbeat: the GCS creation pipeline routes launch
    # storms toward (and debits) warm capacity instead of packing them
    # onto one node whose pool is already drained.
    idle_workers: Dict[str, int] = field(default_factory=dict)


# Actor lifecycle states (reference: gcs.proto ActorTableData.ActorState)
ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"


@dataclass
class ActorInfo:
    actor_id: ActorID
    job_id: JobID
    state: str = ACTOR_PENDING
    address: str = ""          # worker RPC address hosting the actor
    worker_id: Optional[WorkerID] = None
    node_id: Optional[NodeID] = None
    name: str = ""
    namespace: str = ""
    class_name: str = ""
    num_restarts: int = 0
    # Restarts caused by planned node drains / preemptions: they bump
    # num_restarts (the client-side seq epoch must advance) but are NOT
    # charged against max_restarts. Budget = num_restarts - preempted_restarts.
    preempted_restarts: int = 0
    max_restarts: int = 0
    death_cause: str = ""
    # Transient migration hint: the zone of the node this actor is being
    # drained off — replacement placement prefers a matching-zone node
    # (multi-slice DCN locality). Cleared once the actor lands.
    prefer_zone: str = ""
    owner_address: str = ""
    creation_spec: Optional[TaskSpec] = None
    resources: Dict[str, float] = field(default_factory=dict)


# Placement group states
PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_REMOVED = "REMOVED"
PG_RESCHEDULING = "RESCHEDULING"


@dataclass
class PlacementGroupInfo:
    pg_id: PlacementGroupID
    name: str = ""
    strategy: str = "PACK"
    bundles: List[Dict[str, float]] = field(default_factory=list)
    state: str = PG_PENDING
    # bundle index -> NodeID
    bundle_nodes: Dict[int, NodeID] = field(default_factory=dict)
    creator_job: Optional[JobID] = None


@dataclass
class JobInfo:
    job_id: JobID
    driver_address: str = ""
    start_time: float = field(default_factory=time.time)
    end_time: float = 0.0
    alive: bool = True
    entrypoint: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)
