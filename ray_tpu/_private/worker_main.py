"""Worker process entrypoint (reference: python/ray/_private/workers/default_worker.py).

Spawned by the raylet; registers back over RPC, then serves pushed tasks until
told to shut down.
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys


def main():
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s worker[%(process)d] %(name)s: %(message)s")
    # Debugging hook (reference: `ray stack` via py-spy): SIGUSR1 dumps all
    # thread stacks to the worker's log file.
    try:
        import faulthandler
        import signal as _signal
        faulthandler.register(_signal.SIGUSR1, all_threads=True)
    except Exception:
        pass
    # Honor JAX_PLATFORMS for user code in this worker. The TPU-tunnel
    # sitecustomize pins jax_platforms via config.update, which BEATS the
    # env var — so a worker spawned with JAX_PLATFORMS=cpu (CPU test
    # clusters) would still lazily initialize the tunnel backend on its
    # first jit and block on an unreachable tunnel. Mirroring the env into
    # the config restores env-var semantics.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat and "axon" not in plat and "tpu" not in plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass
    raylet_address = os.environ["RAY_TPU_RAYLET_ADDRESS"]
    gcs_address = os.environ["RAY_TPU_GCS_ADDRESS"]
    session_dir = os.environ.get("RAY_TPU_SESSION_DIR", "")

    from ray_tpu._private import rpc
    from ray_tpu._private.config import Config, set_config
    from ray_tpu._private.core_worker import CoreWorker
    from ray_tpu._private.ids import NodeID, WorkerID

    worker_id = WorkerID.from_hex(os.environ["RAY_TPU_WORKER_ID"])
    node_id = NodeID.from_hex(os.environ["RAY_TPU_NODE_ID"])

    async def run():
        config = Config.load()
        core = CoreWorker("worker", gcs_address, raylet_address, config,
                          worker_id=worker_id, node_id=node_id,
                          session_dir=session_dir)
        await core.start_async()
        # Make the public API (ray_tpu.get/put/remote inside tasks) reentrant.
        from ray_tpu._private import worker_api
        worker_api._worker_core.core = core
        # Register with the raylet so it can hand out leases to us. The
        # push handler is live from the first frame: the raylet delivers
        # warm-path actor constructions as a PUSH over this connection
        # (no per-create dial back to our server).
        conn_cell = {}

        async def _instantiate_and_report(payload):
            try:
                result = await core._rpc_instantiate_actor(None, payload)
            except BaseException as e:  # noqa: BLE001
                # Nothing awaits this task: an escaped error would leave
                # the raylet's result future waiting out the full create
                # timeout. Ship it as an infra error instead — the
                # raylet re-raises it into the create path (same
                # semantics the old request/reply dispatch had).
                import traceback
                result = {"_infra_error":
                          f"{type(e).__name__}: {e}\n"
                          f"{traceback.format_exc()}"}
            try:
                # notify, not request: the raylet's result future is the
                # ack (its create path times out if this frame is lost
                # with the connection — same failure semantics).
                await conn_cell["conn"].notify("instantiate_result", {
                    "worker_id": worker_id, "result": result})
            except Exception:
                logging.getLogger(__name__).exception(
                    "instantiate_result report failed")

        def _raylet_push(method, payload):
            if method == "shutdown":
                core.loop.call_soon(core.loop.stop)
            elif method == "instantiate_actor":
                return _instantiate_and_report(payload)

        raylet_conn = await rpc.connect(raylet_address, _raylet_push)
        conn_cell["conn"] = raylet_conn
        reply = await raylet_conn.request("register_worker", {
            "worker_id": worker_id, "pid": os.getpid(),
            "address": core.address,
        })
        set_config(Config.load(reply["config"]))

        assignment = reply.get("assignment")
        if assignment is not None:
            # First assignment rode the registration reply (an actor
            # create was waiting for this worker): construct immediately
            # and report the outcome over this same connection — no
            # idle→re-offer→instantiate dial round trip.
            asyncio.ensure_future(_instantiate_and_report(assignment))

        # The raylet pushes "shutdown" notifications over this connection.
        async def watch_raylet():
            while True:
                await asyncio.sleep(0.5)
                if raylet_conn.closed:
                    core.loop.stop()
                    return
        asyncio.ensure_future(watch_raylet())
        core.server.register("shutdown", _make_shutdown(core))
        return core, raylet_conn

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    core_and_conn = loop.run_until_complete(run())
    core, raylet_conn = core_and_conn

    # raylet "shutdown" / "instantiate_actor" pushes are handled by the
    # push handler installed at connect time (see _raylet_push above);
    # notify-style shutdown also arrives as a request on our server.
    del raylet_conn  # kept alive by the run() closure

    profile_dir = os.environ.get("RAY_TPU_PROFILE_WORKER")
    prof = None
    if profile_dir:
        import cProfile
        import signal as _sig
        prof = cProfile.Profile()

        def _dump_profile(*_a):
            prof.disable()
            prof.dump_stats(
                os.path.join(profile_dir, f"worker-{os.getpid()}.prof"))
            os._exit(0)

        _sig.signal(_sig.SIGTERM, _dump_profile)
        prof.enable()
    try:
        loop.run_forever()
    finally:
        if prof is not None:
            prof.disable()
            prof.dump_stats(
                os.path.join(profile_dir, f"worker-{os.getpid()}.prof"))
        try:
            loop.run_until_complete(core.shutdown_async())
        except Exception:
            pass
        sys.exit(0)


def _make_shutdown(core):
    async def _shutdown(conn, payload):
        core.loop.call_soon(core.loop.stop)
        return True
    return _shutdown


if __name__ == "__main__":
    main()
