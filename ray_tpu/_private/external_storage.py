"""External spill storage: pluggable byte stores for object spilling.

Reference parity: python/ray/_private/external_storage.py — the reference
spills to local disk OR an S3-class URI ("smart_open" URIs); here the same
choice is a Storage implementation keyed by URI scheme. The S3 backend
takes an injectable client (boto3-compatible subset) so it unit-tests with
a mock and gates on boto3 only at real use.

Config: RAY_TPU_SPILL_STORAGE_URI, e.g.
    file:///tmp/spill           (default: the session's spill dir)
    s3://bucket/prefix          (needs boto3 or an injected client)
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

_S3_CLIENT_FACTORY: Optional[Callable] = None


def set_s3_client_factory(factory: Optional[Callable]):
    """Test/deployment hook: inject a boto3-compatible client factory."""
    global _S3_CLIENT_FACTORY
    _S3_CLIENT_FACTORY = factory


class ExternalStorage:
    """put/get/delete of spilled object payloads, keyed by object hex id."""

    def put(self, key: str, data) -> str:
        """Store bytes; returns an opaque locator for get/delete."""
        raise NotImplementedError

    def get(self, locator: str) -> bytes:
        raise NotImplementedError

    def delete(self, locator: str) -> None:
        raise NotImplementedError


class FileStorage(ExternalStorage):
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def put(self, key: str, data) -> str:
        path = os.path.join(self.directory, key)
        with open(path, "wb") as f:
            f.write(data)
        return path

    def get(self, locator: str) -> bytes:
        with open(locator, "rb") as f:
            return f.read()

    def delete(self, locator: str) -> None:
        try:
            os.remove(locator)
        except OSError:
            pass


class S3Storage(ExternalStorage):
    """S3-class bucket spilling (reference: external_storage.py S3 URIs).

    client: boto3-compatible subset — put_object(Bucket, Key, Body),
    get_object(Bucket, Key) -> {"Body": file-like}, delete_object(...).
    Injectable for tests/alternative stacks; without one, boto3 is
    imported at first use (the runtime dependency gate).
    """

    def __init__(self, bucket: str, prefix: str = "", client=None):
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self._client = client

    def _c(self):
        if self._client is None:
            if _S3_CLIENT_FACTORY is not None:
                self._client = _S3_CLIENT_FACTORY()
            else:
                try:
                    import boto3
                except ImportError as e:
                    raise ImportError(
                        "s3:// spill storage requires boto3 (or inject a "
                        "client via set_s3_client_factory)") from e
                self._client = boto3.client("s3")
        return self._client

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def put(self, key: str, data) -> str:
        k = self._key(key)
        self._c().put_object(Bucket=self.bucket, Key=k, Body=bytes(data))
        return f"s3://{self.bucket}/{k}"

    def get(self, locator: str) -> bytes:
        _s, rest = locator.split("://", 1)
        bucket, _, key = rest.partition("/")
        return self._c().get_object(Bucket=bucket, Key=key)["Body"].read()

    def delete(self, locator: str) -> None:
        _s, rest = locator.split("://", 1)
        bucket, _, key = rest.partition("/")
        try:
            self._c().delete_object(Bucket=bucket, Key=key)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass


def storage_from_uri(uri: str, default_dir: str = "") -> ExternalStorage:
    """Build the spill backend for a URI ("" -> local default_dir)."""
    if not uri:
        return FileStorage(default_dir)
    if uri.startswith("file://"):
        return FileStorage(uri[len("file://"):] or default_dir)
    if uri.startswith("s3://"):
        rest = uri[len("s3://"):]
        bucket, _, prefix = rest.partition("/")
        if not bucket:
            raise ValueError(f"bad s3 spill uri {uri!r}")
        return S3Storage(bucket, prefix)
    raise ValueError(f"unsupported spill storage uri {uri!r} "
                     "(file:// or s3://)")
