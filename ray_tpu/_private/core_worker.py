"""CoreWorker: the per-process runtime embedded in the driver and every worker.

Capability parity with the reference CoreWorker (src/ray/core_worker/
core_worker.h, task_manager.h, reference_count.h, transport/): task submission
with lease caching + pipelining (direct_task_transport.h), ordered direct actor
calls with per-caller sequence numbers and restart-aware buffering
(direct_actor_task_submitter.h / actor_scheduling_queue.cc), in-process store
for inlined objects, ownership-based distributed refcounting with borrower
registration, lineage-based object reconstruction (object_recovery_manager.h),
task retries, and task-event export to the GCS.

Runs an asyncio loop: in worker processes it's the main loop; in the driver it
runs on a background thread with a thread-safe sync facade.
"""

from __future__ import annotations

import asyncio
import copy
import logging
import os
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu import exceptions as exc
from ray_tpu.util import tracing as _tracing
from ray_tpu._private import object_ref as object_ref_mod
from ray_tpu._private.flightrec import (IDX_WORKER, N_STAMPS, PH_ARGS_READY,
                                        PH_DISPATCHED, PH_EXEC_END,
                                        PH_EXEC_START, PH_LEASE_GRANTED,
                                        PH_LEASE_WAIT, PH_RECEIVED,
                                        PH_REPLY_HANDLED, PH_RESULT_PUT,
                                        PH_SUBMITTED, PHASE_ORDER,
                                        RECORD_LEN, EventRing)
from ray_tpu._private import rpc
from ray_tpu._private.common import (ACTOR_ALIVE, ACTOR_DEAD, ARG_INLINE,
                                     ARG_REF, ActorInfo, TaskArg, TaskSpec,
                                     TaskSpecTemplate, lease_probe_spec,
                                     wire_spec_batch)
from ray_tpu._private.config import Config
from ray_tpu._private.ids import (ActorID, JobID, NodeID, ObjectID, TaskID,
                                  WorkerID)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store import ObjectStoreClient
from ray_tpu._private.serialization import (SerializationContext,
                                            SerializedObject)

logger = logging.getLogger(__name__)

META_EXCEPTION = b"EXC"

# Marker method occupying a reserved-but-failed actor-task seq slot: the
# receiver advances its ordering cursor without executing anything.
SEQ_SKIP_METHOD = "__ray_tpu_seq_skip__"

# Shared (task_args, kw_names, pin_refs, credits) for zero-arg calls: the
# steady-state `.remote()` hot path allocates nothing for its arguments.
_EMPTY_PREBUILT: tuple = ((), (), (), ())


@dataclass(slots=True)
class OwnedObject:
    """One owned object's refcount/location record. The three collection
    fields are LAZY (shared-empty/None until first use): a steady-state
    task return allocates the record and nothing else — three always-empty
    lists per object were a top allocation site on the submit hot path."""
    object_id: ObjectID
    local_refs: int = 0
    borrowers: int = 0
    # Outstanding handoff credits: borrows pre-registered at serialization
    # time for values that left this process with the ref inside (each is
    # counted in `borrowers` and consumed when the receiver registers).
    handoff_credits: int = 0
    # For locally-stored containers: contained oids credited when THIS
    # object's value was serialized — freeing the container without it
    # ever being deserialized returns those credits. () until assigned.
    credited_contained: Any = ()
    # Where the primary copy lives (raylet addresses); None = nowhere yet.
    locations: Optional[List[str]] = None
    inline_value: Optional[bytes] = None       # serialized, for small objects
    is_exception: bool = False
    # Lineage: spec of the task that created it (for reconstruction).
    creating_spec: Optional[TaskSpec] = None
    ready: bool = False
    # Futures parked on readiness; None until the first waiter.
    waiters: Optional[List[asyncio.Future]] = None
    spilled: bool = False
    reconstructions: int = 0   # lineage re-executions consumed (bounded)

    def add_waiter(self, fut: "asyncio.Future"):
        if self.waiters is None:
            self.waiters = [fut]
        else:
            self.waiters.append(fut)

    def add_location(self, addr: str):
        if self.locations is None:
            self.locations = [addr]
        elif addr not in self.locations:
            self.locations.append(addr)

    def wake_waiters(self):
        if self.waiters:
            for fut in self.waiters:
                if not fut.done():
                    fut.set_result(True)
            self.waiters.clear()


@dataclass(slots=True)
class PendingTask:
    spec: TaskSpec
    retries_left: int = 0
    returns: List[ObjectID] = field(default_factory=list)
    # Holding real ObjectRefs pins arg objects (refcount) until completion.
    # () = none yet (shared empty; the no-arg hot path allocates nothing).
    arg_refs: Any = ()
    # Handoff credits granted when the task's inline args were serialized
    # (self-owned refs contained in arg values). Cleared when the spec
    # actually ships to an executor (the receiver's deserialization
    # consumes them); returned via _return_handoff_credits if the spec is
    # discarded unshipped (cancel/queue-failure) — otherwise the contained
    # objects stay pinned forever (ADVICE r4).
    arg_credits: Any = ()
    # Flight-recorder stamps: a fixed-size list indexed by flightrec's
    # PH_* constants (wall-clock floats; None = not reached; last slot =
    # executing worker hex). Owner-side stamps land here directly;
    # executor stamps merge in from the task reply. A retry overwrites
    # earlier stamps, so the record describes the attempt that actually
    # completed. None until the first stamp (recorder off = never
    # allocated).
    phases: Optional[list] = None


@dataclass(slots=True)
class GeneratorStream:
    """Owner-side state of a streaming-generator task
    (reference: task_manager.h ObjectRefStream, num_returns='streaming')."""
    task_id: TaskID
    spec: Optional[TaskSpec] = None
    # CONTIGUOUS items registered: every index < received has an owned
    # entry. Item notifies can be handled out of order (concurrent
    # handler dispatch), so a plain high-water mark would hand out refs
    # to not-yet-registered indices — their fetch then sees "freed"
    # (found via RPC delay injection on the data suite).
    received: int = 0
    # Registered indices at/after `received` (arrival holes).
    registered_ahead: set = field(default_factory=set)
    total: Optional[int] = None     # set when the task finishes
    error: Optional[Exception] = None
    waiters: List[asyncio.Future] = field(default_factory=list)
    # Producing worker's address (learned from generator_item): lets an
    # abandoned stream cancel the still-running generator task.
    exec_worker: str = ""

    def wake(self):
        for fut in self.waiters:
            if not fut.done():
                fut.set_result(None)
        self.waiters.clear()


@dataclass(slots=True)
class LeaseEntry:
    worker_id: WorkerID
    worker_address: str
    raylet_address: str
    # Tasks pushed but not yet replied; up to config.task_pipeline_depth are
    # pipelined per lease (the worker executes them sequentially).
    inflight: int = 0
    returning: bool = False
    last_used: float = field(default_factory=time.time)
    # EWMA of per-task turnaround on this lease (ms, RPC round trip
    # included); 0 = no sample yet. Gates batch sizing in _pump_queue.
    avg_task_ms: float = 0.0


class ActorSubmitQueue:
    """Client-side per-actor queue: ordered seq numbers, buffering on restart.

    On restart the executing worker resets its per-caller sequence cursor to 0
    (fresh process), so pending (unacknowledged) tasks are renumbered 0..n-1 in
    their original submission order before being re-pushed (reference:
    direct_actor_task_submitter.h resend-on-restart semantics).
    """

    def __init__(self, actor_id: ActorID,
                 lock: Optional[threading.RLock] = None):
        self.actor_id = actor_id
        self.seq = 0
        self.epoch = 0               # observed num_restarts
        self.state = "PENDING"       # PENDING | ALIVE | RESTARTING | DEAD
        self.address = ""
        self.death_reason = ""
        # Sticky marker: the most recent restart was drain/preemption
        # caused. Push failures observed while set are retried WITHOUT
        # consuming max_task_retries (planned loss charges no budgets).
        self.preempted = False
        self.wakeup: List[asyncio.Future] = []
        # seq -> spec of tasks submitted but not yet acknowledged.
        self.inflight: Dict[int, TaskSpec] = {}
        # Push batching: (spec, reply_future, epoch) accumulated within a
        # loop tick flush as ONE push_actor_tasks RPC (reference analogue:
        # direct_actor_task_submitter pipelining; here also one frame).
        self.outbox: List[tuple] = []
        self.flush_scheduled = False
        # Shared with the CoreWorker: seq reservation may happen on a user
        # thread (threadsafe submission) while renumbering runs on the loop.
        self.lock = lock or threading.RLock()

    def next_seq(self) -> int:
        with self.lock:
            s = self.seq
            self.seq += 1
            return s

    def set_state(self, state: str, address: str = "", reason: str = "",
                  num_restarts: int = 0):
        if state == "ALIVE" and num_restarts > self.epoch:
            with self.lock:
                self._renumber_for_epoch(num_restarts)
        self.state = state
        self.address = address
        if reason:
            self.death_reason = reason
        for fut in self.wakeup:
            if not fut.done():
                fut.set_result(None)
        self.wakeup.clear()

    def _renumber_for_epoch(self, num_restarts: int):
        self.epoch = num_restarts
        pending = sorted(self.inflight.items())
        self.inflight = {}
        for new_seq, (_, spec) in enumerate(pending):
            spec.seq_no = new_seq
            self.inflight[new_seq] = spec
        self.seq = len(pending)

    async def wait_for_change(self):
        fut = asyncio.get_running_loop().create_future()
        self.wakeup.append(fut)
        await fut


class CoreWorker:
    """One per process. mode: 'driver' | 'worker'."""

    def __init__(self, mode: str, gcs_address: str, raylet_address: str,
                 config: Config, job_id: Optional[JobID] = None,
                 worker_id: Optional[WorkerID] = None,
                 node_id: Optional[NodeID] = None,
                 session_dir: str = ""):
        self.mode = mode
        self.config = config
        # Inline/shm cutover for puts, task args, and returns: the object
        # plane owns the policy (env-overridable), seeded from config.
        from ray_tpu._private import object_plane as _plane
        self.plane_threshold = _plane.threshold(
            "task", config.max_direct_call_object_size)
        self.gcs_address = gcs_address
        self.raylet_address = raylet_address
        self.job_id = job_id or JobID.from_int(0)
        self.worker_id = worker_id or WorkerID.from_random()
        # Cached hex form: stamped onto every executor phase record and
        # every flushed task event (bytes.hex() per call adds up on the
        # reply hot path).
        self._worker_hex = self.worker_id.hex()
        self.node_id = node_id
        self.session_dir = session_dir
        self.task_id_counter = 0
        self.put_counter = 0
        # current task context (worker side)
        self.current_task_id: Optional[TaskID] = None
        self.current_actor_id: Optional[ActorID] = None

        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None
        self.server = rpc.RpcServer(f"core-{mode}")
        self.address = ""
        self.gcs: Optional[rpc.Connection] = None
        self.raylet: Optional[rpc.Connection] = None
        self.store: Optional[ObjectStoreClient] = None
        self.clients = rpc.ClientPool()
        self.serialization = SerializationContext()
        self.serialization.deserialized_ref_factory = self._make_borrowed_ref
        from ray_tpu._private.serialization import _set_handoff_credit_cb
        _set_handoff_credit_cb(self._grant_handoff_credit,
                               self._return_handoff_credits)

        # object state
        self.owned: Dict[ObjectID, OwnedObject] = {}
        self.borrowed_refs: Dict[ObjectID, Tuple[str, int]] = {}  # oid -> (owner, count)
        self.inproc: Dict[ObjectID, Any] = {}     # deserialized cache
        self._inproc_exc: set = set()  # oids whose cached value is an error
        # Large objects deserialized zero-copy out of shm stay pinned in the
        # local store until their entry leaves the in-process cache
        # (pin COUNT per oid: concurrent resolves each take a store pin).
        self._pinned: Dict[ObjectID, int] = {}
        # In-flight large-object materializations (dedupe concurrent gets).
        self._resolving: Dict[ObjectID, asyncio.Future] = {}

        # task state
        self.pending_tasks: Dict[TaskID, PendingTask] = {}
        self.leases: Dict[tuple, List[LeaseEntry]] = {}
        # Outstanding lease GRANT capacity per sched class (a multi-grant
        # request with count=n contributes n) and the number of lease RPCs
        # carrying it (≤2: one in flight + one standing at the raylet so a
        # freed worker always finds a waiting request).
        self._lease_requests_inflight: Dict[tuple, int] = {}
        self._lease_rpcs_inflight: Dict[tuple, int] = {}
        self._task_queue: Dict[tuple, List[TaskSpec]] = {}
        self._pump_scheduled: set = set()

        # Placement-group readiness: pg_id -> ObjectIDs resolved when the
        # GCS publishes the commit (push-based pg.ready(), no polling).
        self._pg_ready_waiters: Dict[Any, List[ObjectID]] = {}
        self._pg_sub_fut: Optional[asyncio.Future] = None
        # Gang-aware retry: futures woken on any placement_groups state
        # push for a pg_id (created/removed), so tasks that died with
        # their slice wait for the replacement domain instead of spinning
        # lease requests against a mid-reschedule PG.
        self._pg_state_waiters: Dict[Any, List[asyncio.Future]] = {}
        # (pg_id, bundle_index) -> raylet address, resolved via the GCS
        # bundle map once per placement epoch; invalidated on any
        # placement_groups push (and wholesale on node death) so steady-
        # state PG-pinned leases skip the two GCS round trips.
        self._pg_addr_cache: Dict[Any, str] = {}

        # actor state
        self.actor_queues: Dict[ActorID, ActorSubmitQueue] = {}
        self.actor_handles: Dict[ActorID, Any] = {}
        # Refs pinning actor-creation args until instantiation completes.
        self._actor_creation_pins: Dict[ActorID, List[ObjectRef]] = {}
        # In-flight GCS registrations (anonymous creates are
        # fire-and-forget); kill_actor awaits these to avoid racing them.
        self._actor_registrations: Dict[ActorID, asyncio.Future] = {}

        # executor state (worker mode)
        self.executing_actor = None
        self.executing_actor_info: Optional[dict] = None
        self._exec_pool = ThreadPoolExecutor(max_workers=8,
                                             thread_name_prefix="exec")
        self._actor_semaphore: Optional[asyncio.Semaphore] = None
        self._caller_next_seq: Dict[bytes, int] = {}
        self._caller_buffer: Dict[bytes, Dict[int, tuple]] = {}
        self._function_cache: Dict[str, Any] = {}
        # Runtime envs: worker-side applier + driver-side package caches.
        from ray_tpu._private.runtime_env import RuntimeEnvManager
        self.runtime_env_manager = RuntimeEnvManager()
        self._pkg_uri_by_path: Dict[tuple, str] = {}  # (path, sig) -> uri
        self._uploaded_pkgs: set = set()              # uris known in KV
        self._running_tasks: Dict[TaskID, Any] = {}
        self._cancelled_tasks: set = set()
        self.generator_streams: Dict[TaskID, GeneratorStream] = {}
        # Task events: fixed-slot ring written on the submit/reply hot
        # path, folded into wire dicts only at flush (PR 3's recorder at
        # near-zero marginal cost). Spans (tracing.enable()) are rare and
        # keep a plain list.
        self._task_events = EventRing()
        self._span_events: List[dict] = []
        self._te_flush_scheduled = False
        # Drain/preemption awareness (nodes channel): raylet addresses that
        # announced a drain, the event log (Train reads it to classify gang
        # failures), and whether THIS process's node is draining (worker
        # mode: feeds train.should_checkpoint / save-on-preempt).
        self._draining_raylets: set = set()
        self.drain_events: List[dict] = []
        # Push-wakeup hooks fired (on the core loop) when a drain notice
        # lands: the Train preemption watcher parks on an event instead of
        # polling drain_events at 0.25s (see worker_api
        # add_drain_event_listener).
        self.drain_listeners: List[Callable[[], None]] = []
        self.local_node_draining = False
        # Lineage re-executions performed by this owner (drain acceptance
        # tests assert the graceful path keeps this at zero).
        self.reconstructions_total = 0
        self._shutdown = False
        self._bg_tasks: List[asyncio.Task] = []
        # Guards id/seq reservation + owned/pending registration so the
        # threadsafe submission fast paths (user thread) can't race the loop.
        self.submission_lock = threading.RLock()
        # Guards the distributed refcounts (local_refs/borrowers/
        # borrowed_refs): ObjectRef __init__/__del__ fire on ARBITRARY
        # threads and "ent.local_refs += 1" is three bytecodes — an
        # unlocked interleave loses an increment and frees an object that
        # live refs still point to (symptom: intermittent ObjectFreedError
        # / a forever-pending fetch of the freed object, shaken out by
        # RAY_TPU_TESTING_RPC_DELAY_US on the data suite). RLock: GC can
        # re-enter __del__ on the thread already holding it.
        self._ref_lock = threading.RLock()
        # Cross-thread posting with wakeup coalescing: a tight .remote()
        # burst on a user thread pays ONE self-pipe write for the whole
        # burst instead of one per call (~36us of syscall each on this box).
        from collections import deque
        self._ts_inbox: Any = deque()
        self._ts_wake_lock = threading.Lock()
        self._ts_wake_scheduled = False
        # Worker mode: pipelined push_task requests execute one at a time
        # (a leased worker represents one resource grant).
        self._task_exec_lock = asyncio.Lock()

    # ------------------------------------------------------------------
    # lifecycle

    async def start_async(self):
        """Start servers + connections on the current loop."""
        self.loop = asyncio.get_running_loop()
        self._register_handlers()
        port = await self.server.start("127.0.0.1", 0)
        self.address = f"127.0.0.1:{port}"
        self.gcs = rpc.ReconnectingConnection(
            self.gcs_address, self._on_gcs_push,
            on_reconnect=self._on_gcs_reconnect)
        await self.gcs.connect()
        await self.gcs.request("subscribe",
                               {"channels": self._pubsub_channels()})
        self.raylet = await rpc.connect(self.raylet_address)
        # Identify this client so the raylet can reclaim our leases (and
        # the GCS our non-detached actors) if this process goes away.
        # Fire-and-forget (0-RTT bootstrap). NOTE: handlers are only
        # SCHEDULED in frame order, not serialized — correctness does
        # not depend on announce running first: the lease path re-arms
        # _watch_lease_client itself, and a late announce on a closed
        # conn re-runs reclamation (raylet._watch_lease_client).
        try:
            await self.raylet.notify("announce_client",
                                     {"owner_address": self.address})
        except rpc.RpcError:
            pass
        self.store = ObjectStoreClient(self._raylet_request,
                                       self._raylet_notify)
        object_ref_mod._set_core_worker_hooks(
            self._on_ref_created, self._on_ref_deleted,
            self.get_sync, self.get_async)
        self._bg_tasks.append(asyncio.ensure_future(self._flush_task_events_loop()))
        self._bg_tasks.append(asyncio.ensure_future(self._lease_janitor_loop()))
        self._bg_tasks.append(asyncio.ensure_future(self._report_metrics_loop()))
        self._bg_tasks.append(asyncio.ensure_future(self._gcs_liveness_loop()))
        from ray_tpu.util import metrics as metrics_mod
        self._bg_tasks.append(metrics_mod.start_loop_lag_probe(self.mode))

    def _update_pipeline_gauges(self):
        """Depth/inflight gauges over the batching pipeline (tentpole:
        the queues PR 2 built are now observable). Cheap scans, run once
        per report tick, not per task."""
        from ray_tpu.util import metrics as metrics_mod
        g = metrics_mod.Gauge
        g("ray_tpu_task_queue_depth",
          "specs queued for dispatch across scheduling classes").set(
            float(sum(len(q) for q in self._task_queue.values())))
        g("ray_tpu_lease_rpcs_inflight",
          "worker-lease RPCs in flight").set(
            float(sum(self._lease_rpcs_inflight.values())))
        g("ray_tpu_leases_held", "worker leases currently cached").set(
            float(sum(len(v) for v in self.leases.values())))
        # create_actor_threadsafe inserts queues from USER threads: hold
        # the same lock it reserves under, or a storm of anonymous
        # creates resizes the dict mid-iteration and the RuntimeError
        # kills the whole report loop.
        with self.submission_lock:
            outbox_depth = sum(len(q.outbox)
                               for q in self.actor_queues.values())
        g("ray_tpu_actor_outbox_depth",
          "actor-call pushes queued in per-actor outboxes").set(
            float(outbox_depth))
        g("ray_tpu_pending_tasks",
          "tasks submitted by this process and not yet completed").set(
            float(len(self.pending_tasks)))

    async def _report_metrics_loop(self):
        """Refresh pipeline gauges and ship this process's metric registry
        to the GCS periodically (reference: metrics_agent.py push path).
        Only ONE component per process pushes the (process-global)
        registry — when the GCS or a raylet lives in this process it may
        hold the claim instead, and this loop only maintains gauges."""
        from ray_tpu.util import metrics as metrics_mod
        agent = metrics_mod.MetricsAgent(
            f"{self.mode}:{self.worker_id.hex()[:12]}", self.gcs.request)
        while not self._shutdown:
            await asyncio.sleep(self.config.metrics_report_interval_s)
            try:
                self._update_pipeline_gauges()
            except RuntimeError:
                # A user-thread submit resized a dict mid-scan; gauges
                # are best-effort — never let one tick kill the loop.
                pass
            if not self.config.metrics_agent_enabled:
                continue
            if not metrics_mod.claim_reporter(self):
                continue
            rpc.export_transport_metrics()
            snap = metrics_mod.snapshot()
            if not snap:
                continue
            try:
                await agent.ship(snap)
            except rpc.RpcError:
                pass

    def _pubsub_channels(self) -> list:
        channels = ["actors", "nodes"]
        if self._pg_sub_fut is not None:
            # Re-subscribe after a GCS reconnect only if this process ever
            # opted into PG events (see _ensure_pg_subscription).
            channels.append("placement_groups")
        if self.mode == "driver" and self.config.log_to_driver:
            channels.append("logs")
        return channels

    def _ensure_pg_subscription(self):
        """Lazily subscribe to placement_groups pubsub, once.

        Deliberately NOT part of the default channel set: a pg commit
        would otherwise wake every idle worker process in the cluster
        (measured as ~100 ms of context-switch storm per pg op on a
        12-worker single-core box). Only processes that actually wait on
        pg.ready() pay for the events."""
        fut = self._pg_sub_fut
        if fut is not None and fut.done():
            try:
                failed = fut.cancelled() or fut.exception() is not None
            except Exception:  # noqa: BLE001
                failed = True
            if failed:
                fut = None  # retry a failed subscription
        if fut is None:
            self._pg_sub_fut = asyncio.ensure_future(self.gcs.request(
                "subscribe", {"channels": ["placement_groups"]}))
        return self._pg_sub_fut

    async def _on_gcs_reconnect(self, conn: rpc.Connection):
        """Re-establish subscriptions on a fresh (restarted-GCS) connection."""
        await conn.request("subscribe",
                           {"channels": self._pubsub_channels()})
        # pg.ready() waiters registered before the disconnect may have
        # missed their commit push (and the old _check_pg_ready died with
        # the connection): re-run the state race-closer for each.
        for pg_id in list(self._pg_ready_waiters):
            asyncio.ensure_future(self._check_pg_ready(pg_id))
        # Same race-closer for actors: an actor that went ALIVE (or died)
        # while we were reconnecting published its event to nobody — a
        # queue stuck PENDING/RESTARTING would park its calls forever.
        # The subscribe above is already live, so query-then-event can't
        # lose a second transition.
        for actor_id, q in list(self.actor_queues.items()):
            if q.state in ("PENDING", "RESTARTING"):
                asyncio.ensure_future(self._check_actor_state(actor_id))

    async def _check_actor_state(self, actor_id):
        try:
            info = await self.gcs.request("get_actor_info",
                                          {"actor_id": actor_id})
        except rpc.RpcError:
            return
        q = self.actor_queues.get(actor_id)
        if q is None or info is None:
            return
        state = getattr(info, "state", "")
        if state == "ALIVE" and q.state != "ALIVE" and info.address:
            q.set_state("ALIVE", info.address,
                        num_restarts=info.num_restarts)
        elif state == "DEAD" and q.state != "DEAD":
            q.set_state("DEAD", reason="actor died while GCS reconnecting")

    async def _gcs_liveness_loop(self):
        """Active redial of a lost GCS channel. Every consumer of the
        channel (event flush, metrics report) politely SKIPS while it is
        closed, so a process with no explicit GCS calls in flight — e.g.
        a driver whose only work is parked actor calls — would otherwise
        never redial, never re-subscribe, and never learn about actor
        transitions that happened across a GCS restart."""
        while not self._shutdown:
            await asyncio.sleep(1.0)
            g = self.gcs
            if g is None or getattr(g, "_closed", False) or not g.closed:
                continue
            try:
                # Any idempotent request drives _redial + _on_gcs_reconnect
                # (resubscribe + actor/PG state race-closers).
                await g.request("get_status_summary", {})
            except rpc.RpcError:
                pass  # still down; retry next tick (redial backs off)

    async def _raylet_request(self, method, payload):
        return await self.raylet.request(method, payload)

    async def _raylet_notify(self, method, payload):
        await self.raylet.notify(method, payload)

    def start_driver_background(self):
        """Driver mode: run the loop in a daemon thread; block until ready."""
        ready = threading.Event()
        err: List[BaseException] = []

        def _run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self.loop = loop

            async def _boot():
                try:
                    await self.start_async()
                    ready.set()
                except BaseException as e:  # noqa: BLE001
                    err.append(e)
                    ready.set()
            loop.create_task(_boot())
            loop.run_forever()

        self._loop_thread = threading.Thread(target=_run, daemon=True,
                                             name="ray_tpu-core")
        self._loop_thread.start()
        ready.wait(30)
        if err:
            raise err[0]

    def run_sync(self, coro, timeout: Optional[float] = None):
        """Call from a foreign thread into the core loop."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    async def shutdown_async(self):
        self._shutdown = True
        from ray_tpu.util import metrics as metrics_mod
        metrics_mod.release_reporter(self)
        for t in self._bg_tasks:
            t.cancel()
        await self._flush_task_events()
        await self.server.stop()
        await self.clients.close_all()
        if self.store:
            self.store.close()
        for c in (self.gcs, self.raylet):
            if c:
                await c.close()

    def shutdown(self):
        if self.loop is None:
            return
        try:
            self.run_sync(self.shutdown_async(), timeout=10)
        except Exception:
            pass
        if self._loop_thread is not None:
            self.loop.call_soon_threadsafe(self.loop.stop)
            self._loop_thread.join(timeout=5)
        object_ref_mod._set_core_worker_hooks(None, None, None, None)

    def _register_handlers(self):
        s = self.server
        s.register("push_task", self._rpc_push_task)
        s.register("push_task_batch", self._rpc_push_task_batch)
        s.register("push_actor_task", self._rpc_push_actor_task)
        s.register("push_actor_tasks", self._rpc_push_actor_tasks)
        s.register("instantiate_actor", self._rpc_instantiate_actor)
        s.register("kill_actor", self._rpc_kill_actor)
        s.register("cancel_task", self._rpc_cancel_task)
        s.register("owner_locate", self._rpc_owner_locate)
        s.register("owner_add_borrower", self._rpc_owner_add_borrower)
        s.register("owner_remove_borrower", self._rpc_owner_remove_borrower)
        s.register("owner_add_location", self._rpc_owner_add_location)
        s.register("generator_item", self._rpc_generator_item)
        s.register("shutdown", self._rpc_shutdown)
        s.register("ping", self._rpc_ping)
        # On-demand profiling (reference: dashboard reporter
        # profile_manager.py py-spy/memray; here built-in samplers).
        s.register("profile_cpu", self._rpc_profile_cpu)
        s.register("profile_memory", self._rpc_profile_memory)
        s.register("stack_dump", self._rpc_stack_dump)

    @rpc.idempotent
    async def _rpc_profile_cpu(self, conn, payload):
        from ray_tpu.util import profiling
        duration = min(float(payload.get("duration_s", 2.0)), 30.0)
        return await asyncio.get_running_loop().run_in_executor(
            self._exec_pool, lambda: profiling.sample_cpu(duration))

    @rpc.idempotent
    async def _rpc_profile_memory(self, conn, payload):
        from ray_tpu.util import profiling
        return profiling.snapshot_memory(
            top=int(payload.get("top", 30)))

    @rpc.idempotent
    async def _rpc_stack_dump(self, conn, payload):
        from ray_tpu.util import profiling
        return profiling.stack_dump()

    @rpc.idempotent
    async def _rpc_ping(self, conn, payload):
        return {"worker_id": self.worker_id, "mode": self.mode}

    @rpc.idempotent
    async def _rpc_shutdown(self, conn, payload):
        self._shutdown = True
        self.loop.call_soon(self.loop.stop)
        return True

    # ------------------------------------------------------------------
    # GCS pushes (actor + node state)

    def _on_gcs_push(self, method: str, payload):
        if method != "pub":
            return
        channel, msg = payload["channel"], payload["message"]
        if channel == "logs":
            # The (pid=..., node=...) worker-output stream (reference:
            # worker.py print_worker_logs).
            import sys as _sys
            prefix = f"(pid={msg.get('pid')}, node={msg.get('node')})"
            for line in msg.get("lines", []):
                print(f"{prefix} {line}", file=_sys.stderr)
            return
        if channel == "actors":
            if msg.get("event") == "alive_batch":
                # Coalesced ALIVE publishes: one frame carries every
                # creation that completed in that GCS loop tick.
                for info in msg.get("actors", []):
                    q = self.actor_queues.get(info.actor_id)
                    if q is not None:
                        q.set_state("ALIVE", info.address,
                                    num_restarts=info.num_restarts)
                return
            info: Optional[ActorInfo] = msg.get("actor_info")
            actor_id = info.actor_id if info is not None else msg.get("actor_id")
            q = self.actor_queues.get(actor_id)
            if q is None:
                return
            event = msg["event"]
            if event == "alive":
                q.set_state("ALIVE", info.address,
                            num_restarts=info.num_restarts)
            elif event == "restarting":
                # Sticky until the NEXT (non-preempted) restart: push
                # failures straggling in after the ALIVE event still
                # classify as planned loss.
                q.preempted = bool(msg.get("preempted"))
                q.set_state("RESTARTING")
            elif event == "dead":
                # Terminal death is never the drain's doing (migration
                # restarts without charging, so a drained actor cannot
                # exhaust its budget): a genuine crash after an earlier
                # migration must not inherit the sticky preempted flag.
                q.preempted = False
                q.set_state("DEAD", reason=msg.get("reason", "actor died"))
                self._actor_creation_pins.pop(q.actor_id, None)
        elif channel == "placement_groups":
            event = msg.get("event")
            pg_id = msg["pg"].pg_id if "pg" in msg else msg.get("pg_id")
            self._drop_pg_addr_cache(pg_id)
            if event == "created":
                self._resolve_pg_ready(msg["pg"].pg_id, ok=True)
                self._wake_pg_state_waiters(msg["pg"].pg_id)
            elif event == "removed":
                self._resolve_pg_ready(
                    msg.get("pg_id"), ok=False,
                    why="placement group was removed before it was placed")
                self._wake_pg_state_waiters(msg.get("pg_id"))
        elif channel == "nodes":
            event = msg.get("event")
            if event == "gang_draining":
                # A whole slice fault domain is going away at once: mark
                # EVERY member address up front so failures racing the
                # per-member events still classify as planned (uncharged)
                # loss, gang-aware from the first notice.
                addrs = [a for a in (msg.get("addresses") or []) if a]
                node_ids = msg.get("node_ids") or []
                self.drain_events.append({
                    "time": time.time(),
                    "address": addrs[0] if addrs else "",
                    "addresses": addrs,
                    "node_id": node_ids[0] if node_ids else None,
                    "node_ids": node_ids,
                    "slice_id": msg.get("slice_id", ""),
                    "dag_ids": msg.get("dag_ids") or [],
                    "deadline": msg.get("deadline", 0.0)})
                for a in addrs:
                    self._draining_raylets.add(a)
                    self._on_raylet_draining(a)
                if self.node_id is not None and any(
                        nid == self.node_id for nid in node_ids):
                    self.local_node_draining = True
                self._fire_drain_listeners()
            elif event == "draining":
                address = msg.get("address", "")
                self.drain_events.append({
                    "time": time.time(), "address": address,
                    "node_id": msg.get("node_id"),
                    "dag_ids": msg.get("dag_ids") or [],
                    "deadline": msg.get("deadline", 0.0)})
                if address:
                    self._draining_raylets.add(address)
                    self._on_raylet_draining(address)
                if self.node_id is not None \
                        and msg.get("node_id") == self.node_id:
                    # Our own host is going away: surface to the session
                    # layer (Train save-on-preempt).
                    self.local_node_draining = True
                self._fire_drain_listeners()
            elif event == "dead":
                # Reconstruction checks for objects on that node happen
                # lazily (a failed fetch walks the location list itself).
                # Prune the drained-address marker after a grace window:
                # in-flight failures still classify as preemption, but a
                # LATER raylet reusing the same host:port must not have
                # its genuine crashes laundered into uncharged retries.
                nid = msg.get("node_id")
                self._pg_addr_cache.clear()  # bundle homes may have moved
                stale = {ev["address"] for ev in self.drain_events
                         if ev.get("node_id") == nid and ev.get("address")}
                for ev in self.drain_events:
                    if nid in (ev.get("node_ids") or []):
                        idx = ev["node_ids"].index(nid)
                        addrs = ev.get("addresses") or []
                        if idx < len(addrs):
                            stale.add(addrs[idx])
                for addr in stale:
                    self.loop.call_later(
                        15.0, self._draining_raylets.discard, addr)

    def _fire_drain_listeners(self):
        for cb in list(self.drain_listeners):
            try:
                cb()
            except Exception:  # noqa: BLE001 — listeners must not break pubsub
                logger.exception("drain-event listener failed")

    def _on_raylet_draining(self, address: str):
        """Stop routing new tasks through leases on a draining node: drop
        them from the lease tables (in-flight pushes still complete) and
        hand idle ones back so the raylet can reach quiescence."""
        # PG-pinned lease routing must not keep dialing a bundle home
        # that is going away (the re-commit push will refill the cache
        # with the replacement domain's address).
        for key in [k for k, a in self._pg_addr_cache.items()
                    if a == address]:
            self._pg_addr_cache.pop(key, None)
        for sched_class, leases in list(self.leases.items()):
            for lease in list(leases):
                if lease.raylet_address != address:
                    continue
                leases.remove(lease)
                if lease.inflight == 0 and not lease.returning:
                    lease.returning = True

                    async def _ret(entry=lease):
                        try:
                            await self.clients.request(
                                entry.raylet_address, "return_worker",
                                {"worker_id": entry.worker_id}, timeout=5)
                        except rpc.RpcError:
                            pass
                    asyncio.ensure_future(_ret())
            if self._task_queue.get(sched_class):
                self._schedule_pump(sched_class)

    # ==================================================================
    # Object API
    # ==================================================================

    def _next_task_id(self) -> TaskID:
        with self.submission_lock:
            self.task_id_counter += 1
            idx = self.task_id_counter
        return TaskID.for_index(self.job_id, self.worker_id.binary(), idx)

    def _on_ref_created(self, ref: ObjectRef):
        with self._ref_lock:
            ent = self.owned.get(ref.id)
            if ent is not None:
                ent.local_refs += 1
            elif ref.owner_address and ref.owner_address != self.address:
                oid = ref.id
                owner, count = self.borrowed_refs.get(
                    oid, (ref.owner_address, 0))
                self.borrowed_refs[oid] = (owner, count + 1)

    def _on_ref_deleted(self, ref: ObjectRef):
        if self.loop is None or self._shutdown:
            return
        with self._ref_lock:
            ent = self.owned.get(ref.id)
            if ent is not None:
                ent.local_refs -= 1
                if ent.local_refs <= 0 and ent.borrowers <= 0:
                    self._post_to_loop(self._schedule_free, ref.id)
                return
            rec = self.borrowed_refs.get(ref.id)
            if rec is None:
                return
            owner, count = rec
            if count > 1:
                self.borrowed_refs[ref.id] = (owner, count - 1)
                return
            del self.borrowed_refs[ref.id]
            self.inproc.pop(ref.id, None)
            self._inproc_exc.discard(ref.id)
            npins = self._pinned.pop(ref.id, 0)
        if npins:
            oid_bytes = ref.id.binary()

            async def _rel(n=npins, ob=oid_bytes):
                for _ in range(n):
                    await self.store.release(ob)
            try:
                self.loop.call_soon_threadsafe(
                    lambda: asyncio.ensure_future(_rel()))
            except RuntimeError:
                pass
        self._notify_owner_deref(ref.id, owner)

    def _notify_owner_deref(self, oid: ObjectID, owner: str):
        async def _go():
            try:
                conn = await self.clients.get(owner)
                await conn.notify("owner_remove_borrower", {"object_id": oid})
            except Exception:
                pass
        try:
            self.loop.call_soon_threadsafe(lambda: asyncio.ensure_future(_go()))
        except RuntimeError:
            pass

    def _schedule_free(self, oid: ObjectID):
        with self._ref_lock:
            ent = self.owned.get(oid)
            if ent is None or ent.local_refs > 0 or ent.borrowers > 0:
                return
            # Fast path: inline-only object — no store copies to delete,
            # no pins to release, no contained credits to return. Drop it
            # synchronously instead of spawning a _free_object task (one
            # coroutine per dropped ref was a top-3 loop cost in a
            # result-burst profile).
            if (not ent.locations and not ent.credited_contained
                    and not self._pinned.get(oid)):
                self.owned.pop(oid, None)
                self.inproc.pop(oid, None)
                self._inproc_exc.discard(oid)
                return
        asyncio.ensure_future(self._free_object(oid))

    async def _free_object(self, oid: ObjectID):
        followups = []
        with self._ref_lock:
            ent = self.owned.get(oid)
            if ent is not None and (ent.local_refs > 0
                                    or ent.borrowers > 0):
                return  # resurrected between schedule and free
            ent = self.owned.pop(oid, None)
            self.inproc.pop(oid, None)
            self._inproc_exc.discard(oid)
            npins = self._pinned.pop(oid, 0)
            # The container's value was never deserialized: return the
            # handoff credits its serialization granted to contained
            # self-owned refs, or they stay pinned forever.
            if ent is not None:
                for sub in ent.credited_contained:
                    sub_ent = self.owned.get(sub)
                    if sub_ent is not None and sub_ent.handoff_credits > 0:
                        sub_ent.handoff_credits -= 1
                        sub_ent.borrowers -= 1
                        if (sub_ent.local_refs <= 0
                                and sub_ent.borrowers <= 0):
                            followups.append(sub)
        for sub in followups:
            self._schedule_free(sub)
        for _ in range(npins):
            try:
                await self.store.release(oid.binary())
            except Exception:
                pass
        if ent is None:
            return
        for addr in ent.locations or ():
            try:
                conn = await self.clients.get(addr)
                await conn.notify("store_delete", {"object_ids": [oid.binary()]})
            except Exception:
                pass

    def _grant_handoff_credit(self, ref: ObjectRef) -> bool:
        """Serialization hook: a ref to a SELF-OWNED object is leaving the
        process inside a value. Pre-register one borrow (a handoff
        credit) so the object survives until the receiver's own borrow
        registration lands — closes the async-notify window where the
        owner's count hits zero mid-flight."""
        with self._ref_lock:
            ent = self.owned.get(ref.id)
            if ent is None:
                return False  # borrowed/unknown: legacy best-effort path
            ent.borrowers += 1
            ent.handoff_credits += 1
            return True

    def _return_handoff_credits(self, ids):
        """Return handoff credits for serialized bytes that will never be
        deserialized by a receiver (arg-probe discard, cancel before
        dispatch, queued-task failure, failed actor registration).

        Thread-safe: the decrement runs under the ref lock; any resulting
        free is posted to the loop when called from a user thread."""
        if not ids:
            return
        followups = []
        with self._ref_lock:
            for oid in ids:
                ent = self.owned.get(oid)
                if ent is not None and ent.handoff_credits > 0:
                    ent.handoff_credits -= 1
                    ent.borrowers -= 1
                    if ent.local_refs <= 0 and ent.borrowers <= 0:
                        followups.append(oid)
        if not followups:
            return
        try:
            on_loop = asyncio.get_running_loop() is self.loop
        except RuntimeError:
            on_loop = False
        for oid in followups:
            if on_loop:
                self._schedule_free(oid)
            else:
                self._post_to_loop(self._schedule_free, oid)

    def _make_borrowed_ref(self, object_id: ObjectID, owner_address: str,
                           credited: bool = False):
        """Called when a contained ObjectRef is deserialized in this
        process. `credited`: the serializer granted a handoff credit."""
        if object_id in self.owned:
            # Our own object came back to us: the local ObjectRef tracks
            # it; a granted credit is surplus — cancel it.
            if credited:
                with self._ref_lock:
                    ent = self.owned.get(object_id)
                    if ent is not None and ent.handoff_credits > 0:
                        ent.handoff_credits -= 1
                        ent.borrowers -= 1
            return ObjectRef(object_id, owner_address)
        first = object_id not in self.borrowed_refs
        ref = ObjectRef(object_id, owner_address)
        if not owner_address or owner_address == self.address:
            return ref
        payload = None
        if first:
            # Register as borrower; a credit converts into this borrow
            # (owner count unchanged — it was pre-counted at serialize).
            payload = {"object_id": object_id, "handoff": credited}
        elif credited:
            # Already registered: the extra credit must be returned.
            payload = {"object_id": object_id, "handoff": True,
                       "cancel": True}
        if payload is not None:
            async def _reg():
                try:
                    conn = await self.clients.get(owner_address)
                    await conn.notify("owner_add_borrower", payload)
                except Exception:
                    pass
            try:
                asyncio.get_running_loop()
                asyncio.ensure_future(_reg())
            except RuntimeError:
                if self.loop:
                    self.loop.call_soon_threadsafe(
                        lambda: asyncio.ensure_future(_reg()))
        return ref

    # ---- owner protocol handlers ----

    @rpc.idempotent
    async def _rpc_owner_locate(self, conn, payload):
        oid: ObjectID = payload["object_id"]
        ent = self.owned.get(oid)
        if ent is None:
            return {"error": "freed"}
        if not ent.ready:
            fut = asyncio.get_running_loop().create_future()
            ent.add_waiter(fut)
            timeout = payload.get("timeout")
            try:
                await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                return {"error": "timeout"}
            ent = self.owned.get(oid)
            if ent is None:
                return {"error": "freed"}
        return {"inline": ent.inline_value,
                "locations": list(ent.locations or ()),
                "is_exception": ent.is_exception}

    @rpc.non_idempotent
    async def _rpc_owner_add_borrower(self, conn, payload):
        free = False
        oid = payload["object_id"]
        with self._ref_lock:
            ent = self.owned.get(oid)
            if ent is not None:
                if payload.get("cancel"):
                    # surplus handoff credit returned by a receiver that
                    # was already registered
                    if ent.handoff_credits > 0:
                        ent.handoff_credits -= 1
                        ent.borrowers -= 1
                        free = (ent.local_refs <= 0 and ent.borrowers <= 0)
                elif payload.get("handoff") and ent.handoff_credits > 0:
                    # borrow replaces its pre-counted credit: net zero
                    ent.handoff_credits -= 1
                else:
                    ent.borrowers += 1
        if free:
            self._schedule_free(oid)
        return True

    @rpc.non_idempotent
    async def _rpc_owner_remove_borrower(self, conn, payload):
        oid = payload["object_id"]
        with self._ref_lock:
            ent = self.owned.get(oid)
            if ent is not None:
                ent.borrowers -= 1
                free = ent.local_refs <= 0 and ent.borrowers <= 0
            else:
                free = False
        if free:
            self._schedule_free(oid)
        return True

    @rpc.idempotent
    async def _rpc_owner_add_location(self, conn, payload):
        ent = self.owned.get(payload["object_id"])
        if ent is not None:
            ent.add_location(payload["location"])
        return True

    # ---- put / get ----

    def _reserve_put_oid(self) -> ObjectID:
        with self.submission_lock:
            self.put_counter += 1
            counter = self.put_counter
        task_id = self.current_task_id or TaskID.of(self.job_id)
        return ObjectID.for_put(task_id, counter)

    def _register_inline_put(self, oid: ObjectID, value: Any,
                             ser: SerializedObject) -> ObjectRef:
        ent = OwnedObject(object_id=oid, ready=True)
        ent.inline_value = ser.to_bytes()
        ent.credited_contained = list(ser.credited_ids)
        with self.submission_lock:
            self.owned[oid] = ent
            self.inproc[oid] = value
        return ObjectRef(oid, self.address)

    async def put_async(self, value: Any, _pin_object: bool = True) -> ObjectRef:
        oid = self._reserve_put_oid()
        ser = self.serialization.serialize(value)
        if ser.total_size <= self.plane_threshold:
            return self._register_inline_put(oid, value, ser)
        return await self._put_large(oid, ser)

    async def _put_large(self, oid: ObjectID, ser: SerializedObject
                         ) -> ObjectRef:
        ent = OwnedObject(object_id=oid, ready=True)
        ent.credited_contained = list(ser.credited_ids)
        self.owned[oid] = ent
        await self.store.put(oid.binary(), ser, owner_address=self.address)
        ent.add_location(self.raylet_address)
        return ObjectRef(oid, self.address)

    def put_sync(self, value: Any) -> ObjectRef:
        """Thread-safe put. Inline-size values never touch the loop; large
        values serialize on the caller and only the store RPCs cross over."""
        oid = self._reserve_put_oid()
        ser = self.serialization.serialize(value)
        if ser.total_size <= self.plane_threshold:
            return self._register_inline_put(oid, value, ser)
        try:
            on_loop = asyncio.get_running_loop() is self.loop
        except RuntimeError:
            on_loop = False
        if on_loop:
            raise RuntimeError(
                "blocking put() of a large object from the core event loop "
                "(async actor context); await the async API instead")
        return self.run_sync(self._put_large(oid, ser))

    async def get_async(self, ref_or_refs, timeout: Optional[float] = None):
        if isinstance(ref_or_refs, list):
            return await self._get_many(ref_or_refs, timeout)
        return await self._get_one(ref_or_refs, timeout)

    async def _get_many(self, refs: List[ObjectRef],
                        timeout: Optional[float]):
        """Batched get: resolve self-owned inline objects in ONE coroutine
        (per-item waiter futures awaited sequentially — completion time is
        the max, not the sum) instead of a gather() Task per ref (measured
        ~9us/item of pure Task overhead on a 3000-ref burst). Anything
        non-trivial (borrowed, plasma-stored, cached-elsewhere) falls back
        to the general per-ref path."""
        deadline = None if timeout is None else time.time() + timeout
        out = [None] * len(refs)
        waits: List[tuple] = []   # (index, oid, fut)
        slow: List[tuple] = []    # (index, ref)
        for i, ref in enumerate(refs):
            oid = ref.id
            if oid in self.inproc:
                if oid in self._inproc_exc:
                    raise self.inproc[oid]
                out[i] = self.inproc[oid]
                continue
            ent = self.owned.get(oid)
            if ent is None:
                slow.append((i, ref))
                continue
            if not ent.ready:
                fut = asyncio.get_running_loop().create_future()
                ent.add_waiter(fut)
                waits.append((i, oid, fut))
                continue
            if not self._resolve_ready_inline(ent, out, i):
                slow.append((i, ref))
        for i, oid, fut in waits:
            if deadline is None:
                await fut
            else:
                try:
                    await asyncio.wait_for(
                        fut, max(0, deadline - time.time()))
                except asyncio.TimeoutError:
                    raise exc.GetTimeoutError(f"get timed out on {oid}")
            ent = self.owned.get(oid)
            if ent is None or not self._resolve_ready_inline(ent, out, i):
                slow.append((i, refs[i]))
        if slow:
            vals = await asyncio.gather(
                *[self._get_one(r, None if deadline is None
                                else max(0, deadline - time.time()))
                  for _i, r in slow])
            for (i, _r), v in zip(slow, vals):
                out[i] = v
        return out

    def _resolve_ready_inline(self, ent: OwnedObject, out: list,
                              i: int) -> bool:
        """Fill out[i] from a ready inline entry; False -> needs the
        general path (large/plasma object). Raises the stored exception
        exactly like _get_one would."""
        if ent.inline_value is None:
            return False
        oid = ent.object_id
        val = self.serialization.deserialize(ent.inline_value)
        self.inproc[oid] = val
        if ent.is_exception:
            self._inproc_exc.add(oid)
            raise val
        out[i] = val
        return True

    def get_sync(self, ref_or_refs, timeout: Optional[float] = None):
        t = None if timeout is None else timeout + 5
        return self.run_sync(self.get_async(ref_or_refs, timeout), t)

    async def _get_one(self, ref: ObjectRef, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.time() + timeout
        value, is_exception = await self._resolve_object(ref, deadline)
        if is_exception:
            raise value
        return value

    async def get_local_async(self, ref: ObjectRef,
                              timeout: Optional[float] = None):
        """Resolve `ref` from the NODE-LOCAL object plane only: returns a
        1-tuple `(value,)` when this node's store holds the object (pinned
        zero-copy view, same discipline as a full get), or None when it
        does not. Never crosses the network — no owner round trip, no
        remote fetch. The StoreChannel fast path for same-node oversize
        payloads: only the control word rings; the bytes stay in the
        segment they were written to."""
        oid = ref.id
        if oid in self.inproc:
            if oid in self._inproc_exc:
                raise self.inproc[oid]
            return (self.inproc[oid],)
        if not await self.store.contains(oid.binary()):
            return None
        deadline = None if timeout is None else time.time() + timeout
        result = await self._materialize_large(oid, (), self.address,
                                               deadline)
        if result is None:
            return None
        value, is_exception = result
        if is_exception:
            raise value
        return (value,)

    async def _resolve_object(self, ref: ObjectRef,
                              deadline: Optional[float]) -> Tuple[Any, bool]:
        oid = ref.id
        # 1. in-process cache
        if oid in self.inproc:
            return self.inproc[oid], oid in self._inproc_exc
        ent = self.owned.get(oid)
        if ent is not None:
            return await self._resolve_owned(ent, deadline)
        # Borrowed object: ask the owner.
        return await self._resolve_borrowed(ref, deadline)

    async def _resolve_owned(self, ent: OwnedObject, deadline) -> Tuple[Any, bool]:
        oid = ent.object_id
        if not ent.ready:
            fut = asyncio.get_running_loop().create_future()
            ent.add_waiter(fut)
            if deadline is None:
                await fut
            else:
                try:
                    await asyncio.wait_for(fut, max(0, deadline - time.time()))
                except asyncio.TimeoutError:
                    raise exc.GetTimeoutError(f"get timed out on {oid}")
        if ent.inline_value is not None:
            val = self.serialization.deserialize(ent.inline_value)
            self.inproc[oid] = val
            if ent.is_exception:
                self._inproc_exc.add(oid)
            return val, ent.is_exception
        # Large object: fetch via local store (pull from remote if needed).
        result = await self._materialize_large(oid, ent.locations or (),
                                               self.address, deadline)
        if result is None:
            # Primary copies lost -> lineage reconstruction.
            ok = await self._reconstruct(ent)
            if not ok:
                raise exc.ObjectLostError(oid, "all copies lost; "
                                          "reconstruction failed")
            return await self._resolve_owned(self.owned[oid], deadline)
        return result

    async def _resolve_borrowed(self, ref: ObjectRef, deadline) -> Tuple[Any, bool]:
        oid = ref.id
        owner = ref.owner_address or self.address
        timeout = None if deadline is None else max(0.0, deadline - time.time())
        try:
            info = await self.clients.request(
                owner, "owner_locate", {"object_id": oid, "timeout": timeout})
        except rpc.RpcError:
            raise exc.OwnerDiedError(ref)
        if info.get("error") == "timeout":
            raise exc.GetTimeoutError(f"get timed out on {oid}")
        if info.get("error") == "freed":
            raise exc.ObjectFreedError(ref, "object was freed by its owner")
        if info.get("inline") is not None:
            val = self.serialization.deserialize(info["inline"])
            self.inproc[oid] = val
            if info["is_exception"]:
                self._inproc_exc.add(oid)
            return val, info["is_exception"]
        result = await self._materialize_large(oid, info["locations"], owner,
                                               deadline)
        if result is None:
            raise exc.ObjectLostError(ref, "object copies unreachable")
        return result

    async def _materialize_large(self, oid: ObjectID, locations: List[str],
                                 owner: str, deadline) -> Optional[tuple]:
        """Fetch + zero-copy deserialize a large object exactly once per
        process; concurrent callers share the result and one store pin."""
        if oid in self.inproc:
            return self.inproc[oid], oid in self._inproc_exc
        while True:
            inflight = self._resolving.get(oid)
            if inflight is None:
                break
            # Wait under OUR deadline, not the winner's; and if the winner
            # failed (e.g. its shorter timeout expired), fall through and
            # attempt our own fetch rather than inheriting the failure.
            t = None if deadline is None else max(0.0, deadline - time.time())
            try:
                await asyncio.wait_for(asyncio.shield(inflight), timeout=t)
            except asyncio.TimeoutError:
                # Our deadline, not an object failure: don't let the owned
                # path mistake this for lost copies (reconstruction).
                raise exc.GetTimeoutError(f"get timed out on {oid}")
            if oid in self.inproc:
                return self.inproc[oid], oid in self._inproc_exc
        fut = asyncio.get_running_loop().create_future()
        self._resolving[oid] = fut
        try:
            data_meta = await self._fetch_to_local(oid, locations, owner,
                                                   deadline)
            if data_meta is None:
                return None
            view, metadata = data_meta
            val = self.serialization.deserialize(view)
            # Keep the store pin: `val` may alias shm (zero-copy numpy).
            self._pinned[oid] = self._pinned.get(oid, 0) + 1
            self.inproc[oid] = val
            if metadata == META_EXCEPTION:
                self._inproc_exc.add(oid)
            return val, metadata == META_EXCEPTION
        finally:
            self._resolving.pop(oid, None)
            if not fut.done():
                fut.set_result(None)

    async def _fetch_to_local(self, oid: ObjectID, locations: List[str],
                              owner: str, deadline) -> Optional[tuple]:
        """Ensure the object is in the local store; return pinned view."""
        key = oid.binary()
        timeout = 0.05
        if await self.store.contains(key):
            return await self.store.get(key, timeout=None)
        if self.raylet_address in locations:
            # It should be local but isn't sealed yet; wait.
            t = None if deadline is None else max(0.0, deadline - time.time())
            return await self.store.get(key, timeout=t)
        if not locations:
            return None
        try:
            ok = await self.raylet.request("store_fetch_remote", {
                "object_id": key, "locations": list(locations),
                "owner_address": owner}, timeout=120.0)
        except rpc.RpcError:
            # Holder nodes unreachable: treat as lost copies so the owned
            # path can attempt lineage reconstruction.
            ok = False
        if not ok:
            return None
        # Record the new location with the owner.
        if owner == self.address:
            ent = self.owned.get(oid)
            if ent is not None:
                ent.add_location(self.raylet_address)
        else:
            try:
                conn = await self.clients.get(owner)
                await conn.notify("owner_add_location",
                                  {"object_id": oid,
                                   "location": self.raylet_address})
            except Exception:
                pass
        return await self.store.get(key, timeout=timeout)

    async def _reconstruct(self, ent: OwnedObject) -> bool:
        """Lineage reconstruction: resubmit the creating task.

        Reference semantics (object_recovery_manager.h): tasks with
        max_retries=0 are not reconstructable, and reconstruction cycles
        are bounded per object rather than refreshing the retry budget.
        """
        spec = ent.creating_spec
        if spec is None or spec.max_retries == 0:
            return False
        if spec.task_id in self.pending_tasks:
            # A reconstruction of this object is already in flight
            # (concurrent get()s race to _reconstruct): don't resubmit the
            # same TaskSpec twice or burn budget on the duplicate.
            return True
        budget = spec.max_retries if spec.max_retries > 0 else 1
        if ent.reconstructions >= budget:
            return False
        ent.reconstructions += 1
        self.reconstructions_total += 1
        logger.warning("reconstructing object %s by resubmitting task %s",
                       ent.object_id.hex()[:12], spec.name)
        ent.ready = False
        ent.locations = []
        ent.inline_value = None
        self.inproc.pop(ent.object_id, None)
        self._inproc_exc.discard(ent.object_id)
        # Re-register the pending entry: the resubmission may land on a
        # stale cached lease pointing at the dead node's worker, and the
        # worker-death handler consults pending_tasks for retry budget.
        # Arg refs are re-pinned for the re-execution, exactly like the
        # original submission (_finish_task_submission).
        returns = [ObjectID.for_task_return(spec.task_id, i)
                   for i in range(spec.num_returns)]
        self.pending_tasks[spec.task_id] = PendingTask(
            spec=spec, retries_left=1, returns=returns,
            arg_refs=self._pin_arg_refs(spec))
        await self._submit_to_cluster(spec)
        return True

    async def wait_async(self, refs: List[ObjectRef], num_returns: int = 1,
                         timeout: Optional[float] = None,
                         fetch_local: bool = True):
        """ray.wait semantics: (ready, not_ready), order-preserving."""
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        done: set = set()
        pending = {id(r): asyncio.ensure_future(self._await_ready(r))
                   for r in refs}
        start = time.time()
        while len(done) < num_returns:
            remaining = None
            if timeout is not None:
                remaining = timeout - (time.time() - start)
                if remaining <= 0:
                    break
            futs = [f for k, f in pending.items() if k not in done]
            if not futs:
                break
            d, _ = await asyncio.wait(futs, timeout=remaining,
                                      return_when=asyncio.FIRST_COMPLETED)
            if not d:
                break
            # Count successful completions first: if num_returns healthy
            # refs are ready, wait() succeeds deterministically even when a
            # dead-owner ref is also in the list.
            failed = None
            for k, f in pending.items():
                if f.done() and k not in done:
                    if f.exception() is not None:
                        failed = failed or f
                    else:
                        done.add(k)
                if len(done) >= num_returns:
                    break
            if failed is not None and len(done) < num_returns:
                # e.g. OwnerDiedError: the ref can never become ready and
                # its value is unrecoverable — surface instead of reporting
                # "ready" (reference: python/ray/exceptions.py
                # OwnerDiedError).
                for other in pending.values():
                    if not other.done():
                        other.cancel()
                raise failed.exception()
        for f in pending.values():
            if not f.done():
                f.cancel()
        # ray.wait contract: at most num_returns ready refs; surplus completed
        # refs stay in not_ready, order preserved.
        ready = [r for r in refs if id(r) in done][:num_returns]
        ready_set = {id(r) for r in ready}
        not_ready = [r for r in refs if id(r) not in ready_set]
        return ready, not_ready

    async def _await_ready(self, ref: ObjectRef):
        ent = self.owned.get(ref.id)
        if ent is not None:
            if not ent.ready:
                fut = asyncio.get_running_loop().create_future()
                ent.add_waiter(fut)
                await fut
            return True
        if ref.id in self.inproc:
            return True
        # One retry with a short pause before declaring the owner dead: a
        # transient connection reset (owner under load) must not convert a
        # recoverable blip into a terminal OwnerDiedError.
        for attempt in (0, 1):
            try:
                await self.clients.request(
                    ref.owner_address, "owner_locate",
                    {"object_id": ref.id, "timeout": None})
                return True
            except rpc.RpcError:
                if attempt == 0:
                    await asyncio.sleep(0.5)
        raise exc.OwnerDiedError(ref)

    # ---- placement-group readiness (push-based) ----

    def pg_ready_local(self, pg_id) -> ObjectRef:
        """Return a ref resolved when `pg_id` commits (core loop only).

        Push-based: the GCS publishes the commit on the
        `placement_groups` channel and the waiter resolves on that push —
        no polling and no task submission (the old ready() submitted a
        real 0-CPU task through the whole lease path: ~28 ms on a quiet
        3-node cluster vs ~1 ms for the push). One initial state fetch
        covers PGs that committed before this process subscribed."""
        oid = self._reserve_put_oid()
        self.owned[oid] = OwnedObject(object_id=oid)
        self._pg_ready_waiters.setdefault(pg_id, []).append(oid)
        asyncio.ensure_future(self._check_pg_ready(pg_id))
        return ObjectRef(oid, self.address)

    async def _check_pg_ready(self, pg_id):
        """Race-closer for pg_ready_local: subscribe (once), then resolve
        from current GCS state when the commit predates the subscription
        (its pubsub event is gone)."""
        from ray_tpu._private.common import PG_CREATED, PG_REMOVED
        try:
            await asyncio.shield(self._ensure_pg_subscription())
            info = await self.gcs.request("get_placement_group",
                                          {"pg_id": pg_id})
        except rpc.RpcError:
            return  # reconnect path re-subscribes; the push will arrive
        if info is None:
            self._resolve_pg_ready(pg_id, ok=False,
                                   why="placement group does not exist")
        elif info.state == PG_CREATED:
            self._resolve_pg_ready(pg_id, ok=True)
        elif info.state == PG_REMOVED:
            self._resolve_pg_ready(pg_id, ok=False,
                                   why="placement group was removed")

    def _wake_pg_state_waiters(self, pg_id):
        for fut in self._pg_state_waiters.pop(pg_id, []):
            if not fut.done():
                fut.set_result(None)

    async def _pg_state_wait(self, pg_id, delay: float):
        """Park until the next placement_groups push for `pg_id`, or at
        most `delay` seconds (poll fallback for pushes lost to a GCS
        restart)."""
        fut = asyncio.get_running_loop().create_future()
        self._pg_state_waiters.setdefault(pg_id, []).append(fut)
        try:
            await asyncio.wait_for(fut, delay)
        except asyncio.TimeoutError:
            pass
        finally:
            # A wake pops the whole list; on timeout, drop OUR future
            # so retry loops can't grow the entry without bound.
            waiters = self._pg_state_waiters.get(pg_id)
            if waiters is not None:
                if fut in waiters:
                    waiters.remove(fut)
                if not waiters:
                    self._pg_state_waiters.pop(pg_id, None)

    async def _wait_pg_routable(self, pg_id, bundle_index: int,
                                timeout: float) -> Optional[str]:
        """Block until `pg_id` is committed on a raylet we may route to,
        returning that address; None when removed / timed out.

        "Committed" alone is not enough: during a gang drain the GCS
        still reports the PRE-move commit while the bundles sit on
        draining members (the handoff flips state only when migration
        starts), so a created-state check would happily route back into
        the dying slice and waste the retry. Push-driven with a poll
        fallback: a commit that landed before we registered the waiter
        is seen by the state fetch."""
        from ray_tpu._private.common import PG_REMOVED
        deadline = time.monotonic() + timeout
        while not self._shutdown:
            try:
                info = await self.gcs.request("get_placement_group",
                                              {"pg_id": pg_id})
            except rpc.RpcError:
                info = None
            if info is not None and info.state == PG_REMOVED:
                return None
            addr = await self._pg_lease_target(pg_id, bundle_index,
                                               info=info)
            if addr is not None:
                return addr
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            await self._pg_state_wait(pg_id, min(0.5, remaining))
        return None

    def _drop_pg_addr_cache(self, pg_id):
        if pg_id is None:
            return
        for key in [k for k in self._pg_addr_cache if k[0] == pg_id]:
            self._pg_addr_cache.pop(key, None)

    async def _pg_lease_target(self, pg_id, bundle_index: int,
                               info=None) -> Optional[str]:
        """Raylet address hosting a PG bundle, or None when unknown.

        PG-pinned leases dial the raylet that actually holds the bundle
        (the GCS's bundle_nodes map), which is what routes a gang-retried
        task onto the REPLACEMENT fault domain after a slice drain —
        the submitter's local raylet knows nothing about the new home.
        Resolved once per placement epoch: the pubsub handler drops the
        cache entry whenever the PG's placement changes, so the steady
        state costs no GCS round trips. `info` lets a caller that just
        fetched the PG record skip the refetch (_wait_pg_routable polls).
        """
        from ray_tpu._private.common import PG_CREATED
        cached = self._pg_addr_cache.get((pg_id, bundle_index))
        if cached is not None:
            return cached
        try:
            if info is None:
                info = await self.gcs.request("get_placement_group",
                                              {"pg_id": pg_id})
            if info is None or info.state != PG_CREATED:
                return None
            idx = bundle_index if bundle_index >= 0 else \
                next(iter(info.bundle_nodes), None)
            node_id = info.bundle_nodes.get(idx)
            if node_id is None:
                return None
            node = await self.gcs.request("get_node_address",
                                          {"node_id": node_id})
            if node and node.get("alive") and not node.get("draining"):
                addr = node.get("address") or None
                # Never route (or cache) INTO a draining raylet: during a
                # gang drain the GCS may still report the pre-move commit
                # while the bundle's host is going away — treat the
                # bundle as homeless until the handoff re-commits. The
                # GCS-side draining flag covers notices this worker's
                # pubsub hasn't delivered yet; _draining_raylets covers
                # the reverse skew.
                if addr and addr not in self._draining_raylets:
                    self._pg_addr_cache[(pg_id, bundle_index)] = addr
                    return addr
        except rpc.RpcError:
            pass
        return None

    def _resolve_pg_ready(self, pg_id, ok: bool, why: str = ""):
        if pg_id is None:
            return
        oids = self._pg_ready_waiters.pop(pg_id, None)
        if not oids:
            return
        if ok:
            ser = self.serialization.serialize(True).to_bytes()
        else:
            ser = self.serialization.serialize(
                exc.RayTpuSystemError(why)).to_bytes()
        for oid in oids:
            ent = self.owned.get(oid)
            if ent is None or ent.ready:
                continue
            ent.inline_value = ser
            ent.is_exception = not ok
            ent.ready = True
            ent.wake_waiters()

    # ==================================================================
    # Task submission (normal tasks)
    # ==================================================================

    async def export_function(self, func: Any, function_id: str):
        """Push a cloudpickled function/class to the GCS function table.

        Driver-local modules ship by value (serialization.dumps_function) so
        workers on other nodes can deserialize without the driver's sys.path
        — reference: python/ray/_private/function_manager.py export path.
        """
        from ray_tpu._private.serialization import dumps_function
        data = dumps_function(func)
        await self.gcs.request("kv_put", {
            "namespace": "funcs", "key": function_id.encode(), "value": data})

    async def prepare_runtime_env(self, env: dict) -> dict:
        """Driver side: package local dirs -> content-addressed KV uploads,
        stamp the canonical env hash (reference: runtime_env/packaging.py
        upload_package_if_needed)."""
        from ray_tpu._private import runtime_env as re_mod
        env = dict(env)
        wd = env.get("working_dir")
        if wd and not wd.startswith("pkg://"):
            env["working_dir"] = await self._upload_package(wd)
        if env.get("py_modules"):
            env["py_modules"] = [
                p if p.startswith("pkg://") else await self._upload_package(p)
                for p in env["py_modules"]]
        env["_hash"] = re_mod.env_hash(env)
        return env

    async def _upload_package(self, path: str) -> str:
        from ray_tpu._private.runtime_env import package_dir, tree_signature
        path = os.path.abspath(path)
        # Cache key includes a cheap stat signature of the tree so edits
        # after the first submission re-package instead of shipping stale
        # code (reference: packaging.py re-hashes on every upload).
        sig = await asyncio.get_running_loop().run_in_executor(
            self._exec_pool, tree_signature, path)
        uri = self._pkg_uri_by_path.get((path, sig))
        if uri is None:
            uri, data = await asyncio.get_running_loop().run_in_executor(
                self._exec_pool, package_dir, path)
            if uri not in self._uploaded_pkgs:
                key = ("pkg:" + uri[len("pkg://"):]).encode()
                exists = await self.gcs.request("kv_exists", {
                    "namespace": "packages", "key": key})
                if not exists:
                    await self.gcs.request("kv_put", {
                        "namespace": "packages", "key": key, "value": data})
                self._uploaded_pkgs.add(uri)
            self._pkg_uri_by_path[(path, sig)] = uri
        return uri

    async def _fetch_package(self, key: str) -> Optional[bytes]:
        return await self.gcs.request("kv_get", {
            "namespace": "packages", "key": key.encode()})

    async def _ensure_runtime_env(self, env: Optional[dict]):
        if env:
            await self.runtime_env_manager.ensure(env, self._fetch_package)

    async def export_function_raw(self, data: bytes, function_id: str):
        """Push an already-cloudpickled function/class blob to the GCS
        function table (client-server path: the blob was pickled on the
        remote client)."""
        if function_id in self._function_cache:
            return
        await self.gcs.request("kv_put", {
            "namespace": "funcs", "key": function_id.encode(),
            "value": data, "overwrite": False})

    async def _load_function(self, function_id: str):
        if function_id in self._function_cache:
            return self._function_cache[function_id]
        import pickle
        data = await self.gcs.request("kv_get", {
            "namespace": "funcs", "key": function_id.encode()})
        if data is None:
            raise exc.RayTpuSystemError(f"function {function_id} not found")
        func = pickle.loads(data)
        self._function_cache[function_id] = func
        return func

    async def _build_args(self, args: tuple, kwargs: dict
                          ) -> Tuple[List[TaskArg], List[str],
                                     List[ObjectRef], List[ObjectID]]:
        """-> (task_args, kw_names, pin_refs, credits). pin_refs holds the
        refs created here for large inlined-to-plasma args; the CALLER must
        keep them alive (e.g. in PendingTask.arg_refs) until the task
        completes, or the refcounter frees the objects before the worker
        fetches them. `credits` are the handoff credits granted while
        serializing inline args — track them with the spec and return them
        if the bytes are discarded unshipped."""
        if not args and not kwargs:
            return _EMPTY_PREBUILT
        task_args: List[TaskArg] = []
        pin_refs: List[ObjectRef] = []
        credits: List[ObjectID] = []
        serialize_inline = self.serialization.serialize_inline
        limit = self.plane_threshold
        try:
            for v in (args if not kwargs else (*args, *kwargs.values())):
                if isinstance(v, ObjectRef):
                    task_args.append(TaskArg(ARG_REF, object_id=v.id,
                                             owner_address=v.owner_address or self.address))
                    continue
                data = serialize_inline(v, limit)
                if data is None:
                    ser = self.serialization.serialize(v)
                    if ser.total_size > limit:
                        ref = await self.put_async(v)
                        pin_refs.append(ref)
                        task_args.append(TaskArg(ARG_REF, object_id=ref.id,
                                                 owner_address=self.address))
                        continue
                    credits.extend(ser.credited_ids)
                    data = ser.to_bytes()
                task_args.append(TaskArg(ARG_INLINE, data=data))
        except Exception:
            # A later arg failed to serialize: the earlier args' bytes are
            # dead — return their credits before propagating.
            self._return_handoff_credits(credits)
            raise
        return task_args, tuple(kwargs) if kwargs else (), pin_refs, credits

    async def submit_task(self, function_id: str, args: tuple, kwargs: dict,
                          **opts) -> List[ObjectRef]:
        # Threaded-caller path keeps the original semantics: args are
        # serialized BEFORE .remote() returns (mutation-after-submit is
        # safe, serialization errors raise at the callsite).
        prebuilt = await self._build_args(args, kwargs)
        return self.submit_task_local(function_id, args, kwargs,
                                      _prebuilt=prebuilt, **opts)

    def submit_task_local(self, function_id: str, args: tuple, kwargs: dict,
                          *, name: str = "", num_returns: int = 1,
                          resources: Optional[Dict[str, float]] = None,
                          scheduling=None, max_retries: int = -1,
                          retry_exceptions: bool = False,
                          is_generator: bool = False,
                          runtime_env: Optional[dict] = None,
                          export: Optional[Any] = None,
                          _prebuilt=None) -> List[ObjectRef]:
        """Synchronous submission: allocates ids/refs immediately and defers
        arg serialization + cluster dispatch to a background task.

        MUST be called on the core loop thread. This mirrors the reference
        CoreWorker::SubmitTask being non-blocking from the caller's
        perspective, and makes `.remote()` legal inside async actors.
        `export`: optional (func, function_id) exported to the GCS function
        table before dispatch (ordering guarantee for first-time functions).
        """
        from ray_tpu._private.common import SchedulingStrategy
        task_id = self._next_task_id()
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id, name=name,
            function_id=function_id, args=[],
            num_returns=num_returns,
            resources=resources or {"CPU": 1.0},
            scheduling=scheduling or SchedulingStrategy(),
            max_retries=(self.config.task_max_retries_default
                         if max_retries < 0 else max_retries),
            retry_exceptions=retry_exceptions,
            owner_address=self.address, owner_worker_id=self.worker_id,
            is_generator=is_generator, runtime_env=runtime_env,
        )
        self._stamp_trace(spec)
        refs = []
        returns = []
        for i in range(num_returns):
            oid = ObjectID.for_task_return(task_id, i)
            ent = OwnedObject(object_id=oid, creating_spec=spec)
            self.owned[oid] = ent
            returns.append(oid)
            refs.append(ObjectRef(oid, self.address))
        if is_generator:
            # Streamed returns have no refs upfront; items register as
            # they arrive (generator_item) and are consumed via
            # generator_next (reference: ObjectRefStream).
            self.generator_streams[task_id] = GeneratorStream(task_id,
                                                              spec=spec)
        self.pending_tasks[task_id] = PendingTask(
            spec=spec, retries_left=spec.max_retries, returns=returns)
        self._stamp_phase(task_id, PH_SUBMITTED)
        self._record_task_event(spec, "PENDING")
        asyncio.ensure_future(
            self._finish_task_submission(spec, args, kwargs, export,
                                         _prebuilt))
        if is_generator:
            from ray_tpu._private.object_ref import ObjectRefGenerator
            return [ObjectRefGenerator(task_id, self)]
        return refs

    def _try_build_args_sync(self, args: tuple, kwargs: dict):
        """Thread-safe synchronous arg build; None if any arg needs plasma.

        Serializing on the CALLER thread keeps the loop free and preserves
        .remote() copy-on-submit semantics without a cross-thread round trip.
        On abort (an arg needs plasma, or serialization fails) the credits
        granted by the probe serializations are returned — the probe's
        bytes are discarded and _build_args re-serializes from scratch
        (ADVICE r4: the probe credit leaked, pinning contained refs)."""
        if not args and not kwargs:
            return _EMPTY_PREBUILT
        task_args: List[TaskArg] = []
        credits: List[ObjectID] = []
        serialize_inline = self.serialization.serialize_inline
        limit = self.plane_threshold
        try:
            for v in (args if not kwargs else (*args, *kwargs.values())):
                if isinstance(v, ObjectRef):
                    task_args.append(TaskArg(
                        ARG_REF, object_id=v.id,
                        owner_address=v.owner_address or self.address))
                    continue
                data = serialize_inline(v, limit)
                if data is None:
                    ser = self.serialization.serialize(v)
                    if ser.total_size > limit:
                        credits.extend(ser.credited_ids)
                        self._return_handoff_credits(credits)
                        return None  # needs async plasma put; loop path
                    credits.extend(ser.credited_ids)
                    data = ser.to_bytes()
                task_args.append(TaskArg(ARG_INLINE, data=data))
        except Exception:
            self._return_handoff_credits(credits)
            raise
        return task_args, tuple(kwargs) if kwargs else (), (), credits

    def submit_task_threadsafe(self, function_id: str, args: tuple,
                               kwargs: dict, *, name: str = "",
                               num_returns: int = 1,
                               resources: Optional[Dict[str, float]] = None,
                               scheduling=None, max_retries: int = -1,
                               retry_exceptions: bool = False,
                               is_generator: bool = False,
                               runtime_env: Optional[dict] = None,
                               export: Optional[Any] = None) -> List[ObjectRef]:
        """Non-blocking submission from a user (non-loop) thread.

        Reserves ids and registers bookkeeping under the submission lock,
        then hands dispatch to the loop fire-and-forget — no blocking
        cross-thread round trip per call (the round-1 latency killer;
        reference equivalent: CoreWorker::SubmitTask is non-blocking).
        """
        from ray_tpu._private.common import SchedulingStrategy
        prebuilt = self._try_build_args_sync(args, kwargs)
        task_id = self._next_task_id()
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id, name=name,
            function_id=function_id, args=[],
            num_returns=num_returns,
            resources=resources or {"CPU": 1.0},
            scheduling=scheduling or SchedulingStrategy(),
            max_retries=(self.config.task_max_retries_default
                         if max_retries < 0 else max_retries),
            retry_exceptions=retry_exceptions,
            owner_address=self.address, owner_worker_id=self.worker_id,
            is_generator=is_generator, runtime_env=runtime_env,
        )
        self._stamp_trace(spec)
        refs: List[ObjectRef] = []
        returns: List[ObjectID] = []
        with self.submission_lock:
            for i in range(num_returns):
                oid = ObjectID.for_task_return(task_id, i)
                self.owned[oid] = OwnedObject(object_id=oid,
                                              creating_spec=spec)
                returns.append(oid)
                refs.append(ObjectRef(oid, self.address))
            if is_generator:
                self.generator_streams[task_id] = GeneratorStream(task_id,
                                                                  spec=spec)
            self.pending_tasks[task_id] = PendingTask(
                spec=spec, retries_left=spec.max_retries, returns=returns)
        self._stamp_phase(task_id, PH_SUBMITTED)
        self._record_task_event(spec, "PENDING")
        self._post_to_loop(
            self._post_threadsafe_task_submit, spec, args, kwargs, export,
            prebuilt)
        if is_generator:
            from ray_tpu._private.object_ref import ObjectRefGenerator
            return [ObjectRefGenerator(task_id, self)]
        return refs

    def _post_threadsafe_task_submit(self, spec, args, kwargs, export,
                                     prebuilt):
        if (prebuilt is not None and export is None and not spec.runtime_env
                and spec.function_id
                not in getattr(self, "_pending_exports", ())):
            # Fast path (mirror of the actor-task one): args already
            # serialized on the caller, function already exported, no env
            # prep — enqueue straight into the batch pump with NO per-task
            # coroutine (an ensure_future per submission was the dominant
            # loop-side cost of a task burst).
            pt = self.pending_tasks.get(spec.task_id)
            if pt is None:
                self._return_handoff_credits(prebuilt[3])
                return  # cancelled before dispatch
            task_args, kw_names, pin_refs, credits = prebuilt
            spec.args = task_args
            if kw_names:
                spec.kwarg_names = tuple(kw_names)
            pt.arg_refs = self._pin_args(spec, pin_refs)
            pt.arg_credits = credits
            self._enqueue_task_spec(spec)
            return
        asyncio.ensure_future(
            self._finish_task_submission(spec, args, kwargs, export, prebuilt))

    # ---- templated submission (the steady-state `.remote()` fast path) ----

    def submit_task_templated(self, tmpl: TaskSpecTemplate, args: tuple,
                              kwargs: dict) -> List[ObjectRef]:
        """Thread-safe submission for a templated call site.

        The façade pre-resolved every invariant (options, resources,
        scheduling, runtime_env=None, exported function) into `tmpl`;
        a steady-state call stamps task id + args onto a template copy
        and registers bookkeeping — no per-call option dicts, no
        30-kwarg dataclass construction, no per-call coroutine."""
        prebuilt = self._try_build_args_sync(args, kwargs)
        task_id = self._next_task_id()
        if prebuilt is not None:
            task_args, kw_names, pin_refs, credits = prebuilt
            spec = tmpl.make(task_id, task_args,
                             tuple(kw_names) if kw_names else ())
        else:
            spec = tmpl.make(task_id, [])
        ctx = _tracing.current_context()
        if ctx is not None:
            spec.trace_ctx = ctx
        refs: List[ObjectRef] = []
        returns: List[ObjectID] = []
        with self.submission_lock:
            for i in range(tmpl.num_returns):
                oid = ObjectID.for_task_return(task_id, i)
                self.owned[oid] = OwnedObject(object_id=oid,
                                              creating_spec=spec)
                returns.append(oid)
                refs.append(ObjectRef(oid, self.address))
            self.pending_tasks[task_id] = PendingTask(
                spec=spec, retries_left=spec.max_retries, returns=returns)
        if self.config.task_events_enabled:
            now = time.time()  # one clock read feeds stamp AND event
            self._stamp_phase(task_id, PH_SUBMITTED, now)
            self._record_task_event(spec, "PENDING", t=now)
        if prebuilt is not None:
            self._post_to_loop(self._post_templated_task_submit, spec,
                               pin_refs, credits)
        else:
            # An arg needs a plasma put: loop-side serialization path.
            self._post_to_loop(self._post_threadsafe_task_submit, spec,
                               args, kwargs, None, None)
        return refs

    def _post_templated_task_submit(self, spec, pin_refs, credits):
        if spec.function_id in getattr(self, "_pending_exports", ()):
            # A deferred export of this function is still in flight:
            # chain behind it on the slow path.
            asyncio.ensure_future(self._finish_task_submission(
                spec, (), {}, None,
                (spec.args, spec.kwarg_names, pin_refs, credits)))
            return
        pt = self.pending_tasks.get(spec.task_id)
        if pt is None:
            self._return_handoff_credits(credits)
            return  # cancelled before dispatch
        pt.arg_refs = self._pin_args(spec, pin_refs)
        pt.arg_credits = credits
        self._enqueue_task_spec(spec)

    def submit_actor_task_templated(self, tmpl: TaskSpecTemplate,
                                    args: tuple, kwargs: dict
                                    ) -> List[ObjectRef]:
        """Thread-safe actor-call submission for a templated call site
        (same contract as submit_actor_task_threadsafe)."""
        prebuilt = self._try_build_args_sync(args, kwargs)
        actor_id = tmpl.base["actor_id"]
        with self.submission_lock:
            q = self.actor_queues.get(actor_id)
            new_q = q is None
            if new_q:
                q = ActorSubmitQueue(actor_id, self.submission_lock)
                self.actor_queues[actor_id] = q
            seq_no = q.next_seq()
            task_id = TaskID.for_actor_task(self.job_id, actor_id, seq_no,
                                            q.epoch)
            if prebuilt is not None:
                task_args, kw_names, pin_refs, credits = prebuilt
                spec = tmpl.make(task_id, task_args,
                                 tuple(kw_names) if kw_names else (),
                                 seq_no)
            else:
                spec = tmpl.make(task_id, [], seq_no=seq_no)
            ctx = _tracing.current_context()
            if ctx is not None:
                spec.trace_ctx = ctx
            q.inflight[seq_no] = spec
            refs: List[ObjectRef] = []
            returns: List[ObjectID] = []
            for i in range(tmpl.num_returns):
                oid = ObjectID.for_task_return(task_id, i)
                self.owned[oid] = OwnedObject(object_id=oid)
                returns.append(oid)
                refs.append(ObjectRef(oid, self.address))
            self.pending_tasks[task_id] = PendingTask(
                spec=spec, retries_left=spec.max_retries, returns=returns)
        self._stamp_phase(task_id, PH_SUBMITTED)
        if prebuilt is not None:
            self._post_to_loop(self._post_templated_actor_submit, q, spec,
                               pin_refs, credits, new_q)
        else:
            self._post_to_loop(self._post_threadsafe_actor_submit, q, spec,
                               args, kwargs, None, new_q)
        return refs

    def _post_templated_actor_submit(self, q, spec, pin_refs, credits,
                                     new_q):
        if new_q:
            asyncio.ensure_future(self._populate_actor_queue(q))
        pt = self.pending_tasks.get(spec.task_id)
        if pt is None:
            self._return_handoff_credits(credits)
            return  # cancelled before dispatch
        pt.arg_refs = self._pin_args(spec, pin_refs)
        pt.arg_credits = credits
        if q.state == "ALIVE":
            # Fast path: enqueue the push directly, NO per-task coroutine;
            # the batch flusher dispatches the reply.
            self._enqueue_actor_push(q, spec, None)
            return
        asyncio.ensure_future(self._submit_actor_task(q, spec))

    def _post_to_loop(self, fn, *args):
        """call_soon_threadsafe with wakeup coalescing (any thread)."""
        with self._ts_wake_lock:
            self._ts_inbox.append((fn, args))
            if self._ts_wake_scheduled:
                return
            self._ts_wake_scheduled = True
        self.loop.call_soon_threadsafe(self._drain_ts_inbox)

    def _drain_ts_inbox(self, _rearmed: bool = False):
        drained = False
        while True:
            with self._ts_wake_lock:
                if not self._ts_inbox:
                    if drained:
                        # Stay armed one extra tick: a submission burst on
                        # a user thread keeps posting without paying a
                        # self-pipe write per post while the loop is awake
                        # (the wakeup syscall ping-pong was a top caller-
                        # side cost in the n:n profile). One empty round
                        # disarms.
                        self.loop.call_soon(self._drain_ts_inbox, True)
                        return
                    self._ts_wake_scheduled = False
                    return
                items = list(self._ts_inbox)
                self._ts_inbox.clear()
            drained = True
            for fn, args in items:
                try:
                    fn(*args)
                except Exception:
                    logger.exception("posted callback failed")

    async def _await_export(self, export, function_id: str):
        """Serialize deferred function exports: the first submission for a
        function id starts the export; later submissions (which skipped the
        export optimistically) await the same future so no worker can be
        asked to load a function the GCS doesn't have yet."""
        if not hasattr(self, "_pending_exports"):
            self._pending_exports = {}
        if export is not None:
            func, fid = export
            fut = self._pending_exports.get(fid)
            if fut is None:
                fut = asyncio.ensure_future(self.export_function(func, fid))
                self._pending_exports[fid] = fut
            try:
                await fut
            except Exception:
                # Unpoison: drop the failed future and the optimistic
                # "already exported" flag so the next submission retries
                # the export instead of failing forever.
                self._pending_exports.pop(fid, None)
                from ray_tpu._private import worker_api
                worker_api._state.exported_functions.pop(fid, None)
                raise
            self._pending_exports.pop(fid, None)  # GCS has it now
        elif function_id in self._pending_exports:
            await self._pending_exports[function_id]

    async def _finish_task_submission(self, spec: TaskSpec, args, kwargs,
                                      export=None, prebuilt=None):
        try:
            await self._await_export(export, spec.function_id)
            task_args, kw_names, pin_refs, credits = (
                prebuilt if prebuilt is not None
                else await self._build_args(args, kwargs))
        except Exception as e:
            if prebuilt is not None:
                self._return_handoff_credits(prebuilt[3])
            self._complete_task_error(spec, e, retry=False)
            return
        if spec.task_id not in self.pending_tasks:
            self._return_handoff_credits(credits)
            return  # cancelled before dispatch
        spec.args = task_args
        if kw_names:
            spec.kwarg_names = tuple(kw_names)
        if spec.runtime_env:
            spec.runtime_env = await self.prepare_runtime_env(spec.runtime_env)
        pt = self.pending_tasks[spec.task_id]
        pt.arg_refs = self._pin_args(spec, pin_refs)
        pt.arg_credits = credits
        await self._submit_to_cluster(spec)

    def _pin_arg_refs(self, spec: TaskSpec) -> List[ObjectRef]:
        """Task args count as references until the task completes
        (reference semantics: reference_count.h submitted-task references)."""
        return [ObjectRef(a.object_id, a.owner_address)
                for a in spec.args if a.kind == ARG_REF]

    def _pin_args(self, spec: TaskSpec, extra):
        """_pin_arg_refs + the prebuilt pin_refs, allocation-free when the
        spec has no args (the shared-empty-prebuilt hot path)."""
        if not spec.args:
            return list(extra) if extra else ()
        refs = self._pin_arg_refs(spec)
        if extra:
            refs.extend(extra)
        return refs

    def _enqueue_task_spec(self, spec: TaskSpec):
        sched_class = spec.scheduling_class()
        self._task_queue.setdefault(sched_class, []).append(spec)
        self._stamp_phase(spec.task_id, PH_LEASE_WAIT)
        self._schedule_pump(sched_class)

    async def _submit_to_cluster(self, spec: TaskSpec):
        self._enqueue_task_spec(spec)

    def _schedule_pump(self, sched_class: tuple):
        """Run _pump_queue once per loop tick, not once per append: a
        same-tick submission burst accumulates in the queue first, so one
        pump distributes it in batches (otherwise every task pumps a
        1-element queue and ships as its own single-spec RPC — measured as
        one socket send per task)."""
        if sched_class in self._pump_scheduled:
            return
        self._pump_scheduled.add(sched_class)

        def _go():
            self._pump_scheduled.discard(sched_class)
            asyncio.ensure_future(self._pump_queue(sched_class))

        self.loop.call_soon(_go)

    async def _pump_queue(self, sched_class: tuple):
        """Dispatch queued tasks onto cached leases; request more as needed.

        Self-clocking batches (the actor outbox pattern): every pipeline
        slot of a fast lease takes a fair share of whatever queued while
        the previous push was in flight, so a submission trickle converges
        on round-trip-sized batches instead of dribbling out as one-task
        RPCs (measured: a 2000-task burst shipped 1417 singles under the
        old singles-while-inflight rule). Slow/unknown leases take one
        task so queued work stays available for other (incoming) leases
        (reference keeps max_tasks_in_flight_per_worker=1 by default,
        direct_task_transport.h)."""
        queue = self._task_queue.get(sched_class)
        if not queue:
            return
        depth = max(1, self.config.task_pipeline_depth)
        leases = self.leases.setdefault(sched_class, [])
        max_batch = max(1, self.config.task_batch_size)
        n_live = max(1, len(leases))
        for lease in leases:
            while queue and not lease.returning and lease.inflight < depth:
                # Fast leases (sub-5ms turnaround: microtasks) take a
                # fair-share batch per pipeline slot — singles would cost
                # one RPC round trip each; the fair split keeps one lease
                # from soaking the whole queue while peers idle.
                fast = 0 < lease.avg_task_ms < 5.0
                if not fast and lease.inflight > 0:
                    # Slow/unknown lease: one outstanding task only.
                    # Pipelining long tasks onto a cached lease would
                    # serialize them on one worker while the rest of the
                    # cluster idles — leave the remainder queued so the
                    # lease-request block below can fan out instead.
                    break
                take = 1
                if fast:
                    take = min(len(queue), max_batch,
                               max(1, -(-len(queue) // n_live)))
                batch = self._take_batch(queue, take)
                if self.config.task_events_enabled:
                    now = time.time()
                    for spec in batch:
                        self._stamp_phase(spec.task_id,
                                          PH_LEASE_GRANTED, now)
                    self._observe_batch_size("task", len(batch))
                lease.inflight += 1
                asyncio.ensure_future(
                    self._run_on_lease(sched_class, lease, batch))
        if not queue:
            return
        # Lease multi-grant: ONE request carries the backlog as a `count`
        # hint and the raylet replies with up to that many grants — N
        # needed workers cost ~1 RPC round trip, not N (reference:
        # direct_task_transport.h lease pipelining). A second request may
        # overlap so a worker freed mid-round-trip still finds a standing
        # request at the raylet.
        inflight = self._lease_requests_inflight.get(sched_class, 0)
        want = min(len(queue), self.config.max_pending_lease_requests) - inflight
        if want > 0 and self._lease_rpcs_inflight.get(sched_class, 0) < 2:
            self._lease_rpcs_inflight[sched_class] = \
                self._lease_rpcs_inflight.get(sched_class, 0) + 1
            self._lease_requests_inflight[sched_class] = inflight + want
            asyncio.ensure_future(
                self._acquire_lease(sched_class, queue[0], want))

    def _take_batch(self, queue: List[TaskSpec], take: int) -> List[TaskSpec]:
        """Pop up to `take` specs that are safe to ride one batch frame.

        Batch replies are all-or-nothing: the owner learns a batched
        task's result only when the WHOLE batch replies. A spec whose
        ref-arg is a not-yet-ready object of ours could therefore depend
        on a batch-mate — the executor's arg resolution would block on a
        reply that can't ship until the resolution finishes (deadlock
        until timeout). Rule: a batch only carries specs whose ref args
        are all ready-in-owner; an unready/borrowed-arg spec ships alone
        (FIFO order guarantees its producer was shipped earlier)."""
        batch = [queue.pop(0)]
        if not self._batch_safe(batch[0]):
            return batch
        while queue and len(batch) < take and self._batch_safe(queue[0]):
            batch.append(queue.pop(0))
        return batch

    def _batch_safe(self, spec: TaskSpec) -> bool:
        for a in spec.args:
            if a.kind != ARG_REF:
                continue
            if a.owner_address != self.address:
                return False  # can't see a borrowed object's readiness
            ent = self.owned.get(a.object_id)
            if ent is None or not ent.ready:
                return False
        return True

    async def _acquire_lease(self, sched_class: tuple, sample_spec: TaskSpec,
                             count: int = 1):
        try:
            raylet_addr = self.raylet_address
            pg_id = sample_spec.scheduling.placement_group_id
            pg_waited = False
            if pg_id is not None:
                # Route a PG-pinned lease to the raylet holding the
                # bundle (after a slice gang drain this is the
                # replacement fault domain, not anything we ever leased
                # from before).
                addr = await self._pg_lease_target(
                    pg_id, sample_spec.scheduling.bundle_index)
                if addr:
                    raylet_addr = addr
            for _hop in range(8):
                if self._shutdown:
                    return
                try:
                    reply = await self.clients.request(
                        raylet_addr, "request_worker_lease",
                        {"spec": lease_probe_spec(sample_spec),
                         "count": count},
                        timeout=self.config.worker_lease_timeout_s + 10)
                except (rpc.RpcError, OSError) as e:
                    if self._shutdown:
                        return
                    logger.warning("lease request to %s failed: %s", raylet_addr, e)
                    await asyncio.sleep(0.2)
                    continue
                if "grants" in reply or "granted" in reply:
                    for g in reply.get("grants") or [reply["granted"]]:
                        lease = LeaseEntry(worker_id=g["worker_id"],
                                           worker_address=g["worker_address"],
                                           raylet_address=raylet_addr)
                        self.leases.setdefault(sched_class, []).append(lease)
                    return
                if "spillback" in reply:
                    raylet_addr = reply["spillback"]
                    continue
                if "infeasible" in reply:
                    if pg_id is not None and not pg_waited:
                        # The bundle may be mid-handoff (its slice was
                        # drained and the GCS is re-placing the gang):
                        # wait for a commit on a NON-draining home, then
                        # re-route there. The raylet we just dialed said
                        # it cannot host the bundle, so its cached
                        # address is a dead end — drop it FIRST or the
                        # wait would instantly return the same address
                        # from cache (the 'created' push that would have
                        # evicted it may be unprocessed or lost to a GCS
                        # restart). A stale pre-move commit does not
                        # satisfy the wait either (_wait_pg_routable),
                        # so the one allowed wait cannot be burned
                        # routing back into the dying slice. A PG that
                        # never becomes routable fails below instead of
                        # hanging.
                        self._pg_addr_cache.pop(
                            (pg_id, sample_spec.scheduling.bundle_index),
                            None)
                        pg_waited = True
                        addr = await self._wait_pg_routable(
                            pg_id, sample_spec.scheduling.bundle_index,
                            30.0)
                        if addr:
                            raylet_addr = addr
                            continue
                    why = reply.get("why") or (
                        f"no node can satisfy resources "
                        f"{sample_spec.resources}")
                    error: Exception = exc.RayTpuSystemError(why)
                    if reply.get("drained"):
                        # The only node that could host this work was
                        # removed by a planned drain with no live peer.
                        error = exc.NodeDrainedError(None, why)
                    self._fail_queued_tasks(sched_class, error)
                    return
                # retry
                await asyncio.sleep(0.05)
        except (rpc.RpcError, OSError):
            pass
        finally:
            self._lease_requests_inflight[sched_class] = max(
                0, self._lease_requests_inflight.get(sched_class, count)
                - count)
            self._lease_rpcs_inflight[sched_class] = max(
                0, self._lease_rpcs_inflight.get(sched_class, 1) - 1)
            self._schedule_pump(sched_class)

    def _fail_queued_tasks(self, sched_class: tuple, error: Exception):
        queue = self._task_queue.get(sched_class, [])
        while queue:
            spec = queue.pop(0)
            self._complete_task_error(spec, error, retry=False)

    async def _run_on_lease(self, sched_class: tuple, lease: LeaseEntry,
                            specs: List[TaskSpec]):
        """Push a batch of specs to one leased worker.

        Each spec is its own push_task request so replies STREAM back as
        tasks finish (no head-of-line reply blocking for long tasks); the
        requests of a batch go out in the same loop tick, so the rpc
        layer's write coalescing still collapses them into one syscall."""
        t_dispatch = time.time()
        for spec in specs:
            self._record_task_event(spec, "RUNNING")
            # The receiver deserializes the inline args: that consumes the
            # handoff credits (owner_add_borrower handoff=True), so they
            # are no longer ours to return on later failure paths.
            pt = self.pending_tasks.get(spec.task_id)
            if pt is not None:
                pt.arg_credits = []
                if self.config.task_events_enabled:
                    ph = pt.phases
                    if ph is None:
                        ph = pt.phases = [None] * RECORD_LEN
                    ph[PH_DISPATCHED] = t_dispatch
        t_push = time.monotonic()
        try:
            # retry_once=False: the worker may have EXECUTED before the
            # connection died — re-pushing bypasses the retries_left
            # accounting in _handle_task_worker_death (at-most-once).
            if len(specs) == 1:
                push_payload: dict = {"spec": specs[0]}
            else:
                # One RPC round trip covers the whole batch; the worker
                # executes sequentially and replies once. Head-of-line
                # tradeoff: a caller of the first task waits for the whole
                # batch — bounded by task_batch_size (default 8), and
                # batches only form for overflow beyond live lease demand.
                # (A per-item streamed-reply variant measured ~2.4x slower
                # on the microbenchmarks; reply latency lost.)
                # Templated batches ship the invariant spec fields once
                # per frame; the executor decodes them once.
                push_payload = {"specs": wire_spec_batch(specs)}
            if not self.config.task_events_enabled:
                # Owner recorder off: the executor skips its stamps too.
                push_payload["ph"] = 0
            if len(specs) == 1:
                replies = [await self.clients.request(
                    lease.worker_address, "push_task", push_payload,
                    timeout=None, retry_once=False)]
            else:
                replies = await self.clients.request(
                    lease.worker_address, "push_task_batch",
                    push_payload, timeout=None, retry_once=False)
        except rpc.RpcError:
            lease.inflight -= 1
            self._drop_lease(sched_class, lease)
            for spec in specs:
                self._handle_task_worker_death(spec, lease.raylet_address)
            return
        lease.inflight -= 1
        lease.last_used = time.time()
        per_task_ms = (time.monotonic() - t_push) * 1000.0 / len(specs)
        lease.avg_task_ms = (per_task_ms if lease.avg_task_ms == 0.0
                             else 0.5 * lease.avg_task_ms + 0.5 * per_task_ms)
        for spec, reply in zip(specs, replies):
            self._handle_task_reply(spec, reply, lease.raylet_address)
        queue = self._task_queue.get(sched_class, [])
        if queue:
            self._schedule_pump(sched_class)
        else:
            asyncio.ensure_future(self._maybe_return_lease(sched_class, lease))

    async def _maybe_return_lease(self, sched_class: tuple, lease: LeaseEntry):
        await asyncio.sleep(self.config.idle_worker_lease_timeout_s)
        await self._return_lease(sched_class, lease)

    async def _return_lease(self, sched_class: tuple, lease: LeaseEntry):
        if lease.inflight > 0 or lease.returning:
            return
        if self._task_queue.get(sched_class, []):
            return
        lease.returning = True
        self._drop_lease(sched_class, lease)
        try:
            await self.clients.request(lease.raylet_address, "return_worker",
                                       {"worker_id": lease.worker_id}, timeout=5)
        except rpc.RpcError:
            pass

    async def _lease_janitor_loop(self):
        """Return leases that sat idle past the reuse window.

        Covers leases granted after their queue drained (the submitter may
        acquire more leases than tasks remain); reference equivalent:
        lease idle timeout in direct_task_transport.h.
        """
        while not self._shutdown:
            await asyncio.sleep(self.config.idle_worker_lease_timeout_s)
            now = time.time()
            for sched_class, leases in list(self.leases.items()):
                for lease in list(leases):
                    if (lease.inflight == 0 and not lease.returning and
                            now - lease.last_used >
                            self.config.idle_worker_lease_timeout_s):
                        asyncio.ensure_future(
                            self._return_lease(sched_class, lease))

    def _drop_lease(self, sched_class: tuple, lease: LeaseEntry):
        leases = self.leases.get(sched_class, [])
        if lease in leases:
            leases.remove(lease)

    def _handle_task_worker_death(self, spec: TaskSpec,
                                  raylet_address: str = ""):
        pt = self.pending_tasks.get(spec.task_id)
        preempted = raylet_address in self._draining_raylets
        if pt is not None and preempted:
            # Planned node loss (drain / spot reclaim): retry without
            # consuming the task's max_retries budget — the user had no
            # hand in this failure and the cluster had advance notice.
            # DESIGN TRADEOFF: this applies even at max_retries=0, so a
            # task that executed before its reply was lost to the drain
            # runs again (at-least-once under preemption). Preemption
            # survival is the contract here; tasks needing strict
            # at-most-once must be idempotent on preemptible capacity.
            logger.warning("task %s lost to draining node %s; retrying "
                           "(budget uncharged)", spec.name, raylet_address)
            asyncio.ensure_future(self._submit_to_cluster(spec))
        elif pt is not None and pt.retries_left > 0:
            pt.retries_left -= 1
            logger.warning("task %s worker died; retrying (%d left)",
                           spec.name, pt.retries_left)
            asyncio.ensure_future(self._submit_to_cluster(spec))
        else:
            self._complete_task_error(spec, exc.WorkerCrashedError(
                f"worker died while running task {spec.name}",
                preempted=preempted), retry=False)

    def _merge_exec_phases(self, spec: TaskSpec, wphases):
        if wphases is None or not self.config.task_events_enabled:
            return
        pt = self.pending_tasks.get(spec.task_id)
        if pt is not None:
            ph = pt.phases
            if ph is None:
                ph = pt.phases = [None] * RECORD_LEN
            for i in range(PH_RECEIVED, RECORD_LEN):
                v = wphases[i]
                if v is not None:
                    ph[i] = v

    def _handle_task_reply(self, spec: TaskSpec, reply,
                           exec_raylet: str):
        if type(reply) is tuple:
            # Flat success envelope (returns, phases): the steady-state
            # path — no dict lookups, return slots resolved straight from
            # the pending record.
            returns, wphases = reply
            self._merge_exec_phases(spec, wphases)
            self._complete_task_ok(spec, returns, exec_raylet)
            return
        self._merge_exec_phases(spec, reply.get("phases"))
        if reply.get("cancelled"):
            self._complete_task_error(spec, exc.TaskCancelledError(spec.task_id),
                                      retry=False)
            return
        error = reply.get("system_error")
        if error is not None:
            logger.warning("task %s system error: %s", spec.name, error)
            self._handle_task_worker_death(spec, exec_raylet)
            return
        app_error = reply.get("app_error")
        if app_error is not None:
            pt = self.pending_tasks.get(spec.task_id)
            if spec.retry_exceptions and pt is not None and pt.retries_left > 0:
                pt.retries_left -= 1
                asyncio.ensure_future(self._submit_to_cluster(spec))
                return
            self._complete_task_error(spec, app_error, retry=False)
            return
        if "generator_done" in reply:
            self.pending_tasks.pop(spec.task_id, None)
            self._record_task_event(spec, "FINISHED")
            stream = self.generator_streams.get(spec.task_id)
            if stream is not None:
                stream.total = reply["generator_done"]
                stream.wake()
            return
        # Legacy dict-form success envelope: convert its rows to the flat
        # record shape so the "decoders handle both" contract holds (an
        # old-version executor replying dict-form must not hang the get).
        returns = [r if type(r) is tuple else
                   (r.get("inline"), r.get("stored"),
                    bool(r.get("is_exception")))
                   for r in reply["returns"]]
        self._complete_task_ok(spec, returns, exec_raylet)

    def _register_return_object(self, spec: TaskSpec, index: int, ret,
                                exec_raylet: str,
                                oid: Optional[ObjectID] = None) -> ObjectID:
        """Make return slot `index` of `spec` a ready owned object.

        `ret` is a flat (inline_bytes|None, stored_addr|None, is_exception)
        record; `oid` lets completion reuse the ObjectID already held in
        PendingTask.returns instead of re-deriving it."""
        if oid is None:
            oid = ObjectID.for_task_return(spec.task_id, index)
        ent = self.owned.get(oid)
        if ent is None:
            ent = OwnedObject(object_id=oid, creating_spec=spec)
            self.owned[oid] = ent
        inline, stored, is_exc = ret
        if inline is not None:
            ent.inline_value = inline
        else:
            ent.add_location(stored or exec_raylet)
        ent.is_exception = is_exc
        ent.ready = True
        ent.wake_waiters()
        return oid

    @rpc.idempotent
    async def _rpc_generator_item(self, conn, payload):
        """Owner side: one streamed item from an executing generator task."""
        task_id: TaskID = payload["task_id"]
        stream = self.generator_streams.get(task_id)
        if stream is None or stream.spec is None:
            return False  # stream consumed/cancelled; drop late items
        self._register_return_object(stream.spec, payload["index"],
                                     payload["ret"],
                                     payload.get("exec_raylet", ""))
        stream.exec_worker = payload.get("exec_worker", stream.exec_worker)
        stream.registered_ahead.add(payload["index"])
        while stream.received in stream.registered_ahead:
            stream.registered_ahead.discard(stream.received)
            stream.received += 1
        stream.wake()
        return True

    async def generator_next(self, task_id: TaskID,
                             cursor: int) -> Optional[ObjectRef]:
        """Next ref of a streaming task, or None when exhausted (blocking
        form of generator_try_next)."""
        while True:
            kind, ref = await self.generator_try_next(task_id, cursor)
            if kind == "item":
                return ref
            if kind == "done":
                return None
            stream = self.generator_streams.get(task_id)
            if stream is None:
                return None
            fut = asyncio.get_running_loop().create_future()
            stream.waiters.append(fut)
            await fut

    async def generator_try_next(self, task_id: TaskID, cursor: int):
        """Non-blocking generator_next: ("item", ref) | ("pending", None) |
        ("done", None). Lets pull-based consumers (Data streaming reads)
        poll without parking a thread per stream."""
        stream = self.generator_streams.get(task_id)
        if stream is None:
            return ("done", None)
        if cursor < stream.received:
            return ("item",
                    ObjectRef(ObjectID.for_task_return(task_id, cursor),
                              self.address))
        if stream.error is not None:
            raise stream.error
        if stream.total is not None and cursor >= stream.total:
            self.generator_streams.pop(task_id, None)
            return ("done", None)
        return ("pending", None)

    def release_generator(self, task_id: TaskID, consumed: int):
        """Consumer dropped the ObjectRefGenerator: free the stream and the
        never-handed-out return objects (indices >= consumed). Items the
        consumer did take are governed by normal ref counting. A producer
        still running (total unset) gets a best-effort cancel so an
        unbounded generator doesn't stream to nobody forever."""
        stream = self.generator_streams.pop(task_id, None)
        if stream is None:
            return
        stream.wake()
        # never-handed-out items: the contiguous tail plus arrival holes
        unconsumed = set(range(consumed, stream.received))
        unconsumed.update(i for i in stream.registered_ahead
                          if i >= consumed)
        for i in unconsumed:
            self.owned.pop(ObjectID.for_task_return(task_id, i), None)
        if stream.total is None and stream.exec_worker:
            async def _cancel(addr=stream.exec_worker, tid=task_id):
                try:
                    await self.clients.request(
                        addr, "cancel_task", {"task_id": tid}, timeout=5)
                except Exception:  # noqa: BLE001 — best effort
                    pass
            asyncio.ensure_future(_cancel())

    def _complete_task_ok(self, spec: TaskSpec, returns: list,
                          exec_raylet: str):
        pt = self.pending_tasks.pop(spec.task_id, None)
        phases = self._finish_phase_record(pt)
        self._record_task_event(spec, "FINISHED", phases)
        oids = (pt.returns if pt is not None
                and len(pt.returns) == len(returns) else None)
        for i, ret in enumerate(returns):
            self._register_return_object(
                spec, i, ret, exec_raylet,
                oids[i] if oids is not None else None)

    def _complete_task_error(self, spec: TaskSpec, error: Exception,
                             retry: bool):
        pt = self.pending_tasks.pop(spec.task_id, None)
        if pt is not None and pt.arg_credits:
            # Spec died before its arg bytes ever shipped (queue failure,
            # cancel, export error): return the serialize-time credits or
            # the contained objects stay pinned forever (ADVICE r4).
            self._return_handoff_credits(pt.arg_credits)
            pt.arg_credits = []
        # observe=True: failed tasks fold into ray_tpu_task_phase_seconds
        # too, so /metrics agrees with /api/latency (both read "the same
        # record") and a latency alert fires for slow failures as well.
        self._record_task_event(spec, "FAILED",
                                self._finish_phase_record(pt))
        stream = self.generator_streams.get(spec.task_id)
        if stream is not None:
            stream.error = error
            stream.wake()
        ser = self.serialization.serialize(error).to_bytes()
        for i in range(spec.num_returns):
            oid = ObjectID.for_task_return(spec.task_id, i)
            ent = self.owned.get(oid)
            if ent is None:
                continue
            ent.inline_value = ser
            ent.is_exception = True
            ent.ready = True
            ent.wake_waiters()

    async def cancel_task(self, ref: ObjectRef, force: bool = False):
        task_id = ref.id.task_id()
        pt = self.pending_tasks.get(task_id)
        if pt is None:
            return
        # Remove from queue if not yet dispatched.
        sched_class = pt.spec.scheduling_class()
        queue = self._task_queue.get(sched_class, [])
        if pt.spec in queue:
            queue.remove(pt.spec)
            self._complete_task_error(pt.spec, exc.TaskCancelledError(task_id),
                                      retry=False)
            return
        # Running: ask executors to cancel.
        for leases in self.leases.values():
            for lease in leases:
                try:
                    await self.clients.request(
                        lease.worker_address, "cancel_task",
                        {"task_id": task_id, "force": force}, timeout=5)
                except rpc.RpcError:
                    pass

    # ==================================================================
    # Actor API
    # ==================================================================

    async def create_actor(self, class_function_id: str, args: tuple,
                           kwargs: dict, **opts) -> ActorID:
        prebuilt = await self._build_args(args, kwargs)
        actor_id, done = self.create_actor_local(class_function_id, args,
                                                 kwargs, _prebuilt=prebuilt,
                                                 **opts)
        if opts.get("name"):
            # Named creation: surface "name already taken" at the call
            # site (get_if_exists and user code branch on it).
            await done
        else:
            # Anonymous creation is fire-and-forget — a launch storm of N
            # `.remote()` calls must not pay N serial GCS round trips in
            # the caller (measured: the submit loop, not the cluster, was
            # capping the storm). Registration failures surface through
            # the actor queue (DEAD => method calls raise), same as the
            # on-loop path has always behaved.
            done.add_done_callback(
                lambda f: f.exception() if not f.cancelled() else None)
        return actor_id

    def create_actor_local(self, class_function_id: str, args: tuple,
                           kwargs: dict, *, class_name: str = "",
                           resources: Optional[Dict[str, float]] = None,
                           scheduling=None, max_restarts: int = 0,
                           max_task_retries: int = 0, max_concurrency: int = 1,
                           is_async: bool = False, name: str = "",
                           namespace: str = "", lifetime: str = "",
                           runtime_env: Optional[dict] = None,
                           concurrency_groups: Optional[dict] = None,
                           execute_out_of_order: bool = False,
                           method_options: Optional[dict] = None,
                           export: Optional[Any] = None, _prebuilt=None,
                           _actor_id: Optional[ActorID] = None,
                           _queue: Optional["ActorSubmitQueue"] = None):
        """Synchronous actor creation: returns (actor_id, done_future).

        Must run on the core loop thread. Arg serialization, optional class
        export, and GCS registration run in the background; method calls
        submitted before registration park in the submit queue until the
        actor goes ALIVE (or DEAD on registration failure).

        `_actor_id`/`_queue` carry reservations a threadsafe caller
        (create_actor_threadsafe) already made on its own thread — method
        calls submitted against that id before this runs must land in
        the SAME queue, not be clobbered by a fresh one.
        """
        from ray_tpu._private.common import SchedulingStrategy
        actor_id = _actor_id if _actor_id is not None \
            else ActorID.of(self.job_id)
        task_id = self._next_task_id()
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id, name=class_name,
            function_id=class_function_id, args=[],
            resources=resources or {"CPU": 1.0},
            scheduling=scheduling or SchedulingStrategy(),
            owner_address=self.address, owner_worker_id=self.worker_id,
            actor_id=actor_id, is_actor_creation=True,
            max_restarts=max_restarts, max_task_retries=max_task_retries,
            max_concurrency=max_concurrency, is_async_actor=is_async,
            actor_name=name, namespace=namespace, lifetime=lifetime,
            runtime_env=runtime_env, concurrency_groups=concurrency_groups,
            execute_out_of_order=execute_out_of_order,
            method_options=method_options,
        )
        q = _queue if _queue is not None \
            else ActorSubmitQueue(actor_id, self.submission_lock)
        self.actor_queues[actor_id] = q
        done = asyncio.ensure_future(
            self._finish_actor_creation(q, spec, args, kwargs, lifetime,
                                        export, _prebuilt))
        # Registration is fire-and-forget for anonymous creates: remember
        # the in-flight future so GCS-side operations issued right after
        # .remote() (kill, in particular) can await it instead of
        # no-opping on an actor the GCS hasn't heard of yet.
        self._actor_registrations[actor_id] = done
        done.add_done_callback(
            lambda _f, a=actor_id: self._actor_registrations.pop(a, None))
        return actor_id, done

    def create_actor_threadsafe(self, class_function_id: str, args: tuple,
                                kwargs: dict, **opts) -> Optional[ActorID]:
        """Non-blocking actor creation from a user (non-loop) thread.

        Same contract as create_actor, minus the wait: args serialize on
        THIS thread, the actor id + submit queue reserve under the
        submission lock, and registration is handed to the loop
        fire-and-forget — a 1k-actor launch storm pays 1k lock-guarded
        reservations instead of 1k cross-thread round trips through a
        busy loop (measured: the submit loop, not the cluster, capped the
        storm). Returns None when an arg needs the loop (plasma-sized) —
        the caller falls back to the blocking path. Registration failures
        surface through the actor queue (DEAD => method calls raise)."""
        prebuilt = self._try_build_args_sync(args, kwargs)
        if prebuilt is None:
            return None
        with self.submission_lock:
            actor_id = ActorID.of(self.job_id)
            q = ActorSubmitQueue(actor_id, self.submission_lock)
            self.actor_queues[actor_id] = q

        def _go():
            _aid, done = self.create_actor_local(
                class_function_id, args, kwargs, _prebuilt=prebuilt,
                _actor_id=actor_id, _queue=q, **opts)
            done.add_done_callback(
                lambda f: f.exception() if not f.cancelled() else None)

        self.loop.call_soon_threadsafe(_go)
        return actor_id

    async def _finish_actor_creation(self, q: "ActorSubmitQueue",
                                     spec: TaskSpec, args, kwargs,
                                     lifetime: str, export=None,
                                     prebuilt=None):
        credits: List[ObjectID] = list(prebuilt[3]) if prebuilt else []
        try:
            await self._await_export(export, spec.function_id)
            task_args, kw_names, pin_refs, credits = (
                prebuilt if prebuilt is not None
                else await self._build_args(args, kwargs))
            spec.args = task_args
            spec.kwarg_names = tuple(kw_names)
            if spec.runtime_env:
                spec.runtime_env = await self.prepare_runtime_env(
                    spec.runtime_env)
            # Creation args must survive as long as the actor can be
            # (re)instantiated — restarts re-fetch them — so the pins are
            # released only on the DEAD pubsub event.
            self._actor_creation_pins[spec.actor_id] = \
                self._pin_args(spec, pin_refs)
            await self.gcs.request("register_actor", {"spec": spec})
        except BaseException as e:
            # Spec never reached an executor: its inline-arg credits would
            # pin the contained objects forever. BaseException, not
            # Exception: this coroutine runs fire-and-forget on the core
            # loop, and a CancelledError landing mid-register (driver
            # shutdown racing a create) must return the credits too.
            self._return_handoff_credits(credits)
            q.set_state("DEAD", reason=f"actor registration failed: {e!r}")
            raise

    async def submit_actor_task(self, actor_id: ActorID, method_name: str,
                                args: tuple, kwargs: dict,
                                num_returns: int = 1,
                                max_task_retries: int = 0) -> List[ObjectRef]:
        prebuilt = await self._build_args(args, kwargs)
        return self.submit_actor_task_local(actor_id, method_name, args,
                                            kwargs, num_returns,
                                            max_task_retries,
                                            _prebuilt=prebuilt)

    def submit_actor_task_local(self, actor_id: ActorID, method_name: str,
                                args: tuple, kwargs: dict,
                                num_returns: int = 1,
                                max_task_retries: int = 0,
                                concurrency_group: str = "",
                                is_generator: bool = False,
                                _prebuilt=None) -> List[ObjectRef]:
        """Synchronous actor-task submission (core loop thread only).

        The sequence number is reserved and the spec registered in the
        inflight map immediately, so concurrent submissions cannot
        duplicate/skip seq numbers and restart renumbering sees every
        reserved slot. Arg serialization + the network send run in the
        background; the receiver reorders by seq_no, so out-of-order sends
        (args of call N+1 serializing faster than call N's) are safe.
        """
        q = self._ensure_actor_queue(actor_id)
        seq_no = q.next_seq()
        task_id = TaskID.for_actor_task(self.job_id, actor_id, seq_no, q.epoch)
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id, name=method_name,
            args=[], num_returns=num_returns,
            owner_address=self.address, owner_worker_id=self.worker_id,
            actor_id=actor_id, method_name=method_name, seq_no=seq_no,
            max_retries=max_task_retries, concurrency_group=concurrency_group,
            is_generator=is_generator,
        )
        self._stamp_trace(spec)
        q.inflight[seq_no] = spec
        refs, returns = [], []
        for i in range(num_returns):
            oid = ObjectID.for_task_return(task_id, i)
            self.owned[oid] = OwnedObject(object_id=oid)
            returns.append(oid)
            refs.append(ObjectRef(oid, self.address))
        if is_generator:
            self.generator_streams[task_id] = GeneratorStream(task_id,
                                                              spec=spec)
        self.pending_tasks[task_id] = PendingTask(
            spec=spec, retries_left=max_task_retries, returns=returns)
        self._stamp_phase(task_id, PH_SUBMITTED)
        asyncio.ensure_future(
            self._finish_actor_task_submission(q, spec, args, kwargs,
                                               _prebuilt))
        if is_generator:
            from ray_tpu._private.object_ref import ObjectRefGenerator
            return [ObjectRefGenerator(task_id, self)]
        return refs

    def submit_actor_task_threadsafe(self, actor_id: ActorID,
                                     method_name: str, args: tuple,
                                     kwargs: dict, num_returns: int = 1,
                                     max_task_retries: int = 0,
                                     concurrency_group: str = "",
                                     is_generator: bool = False
                                     ) -> List[ObjectRef]:
        """Non-blocking actor-task submission from a user (non-loop) thread.

        Same contract as submit_actor_task_local, but callable from any
        thread: args serialize on the caller, seq/ids reserve under the
        submission lock, and dispatch is handed to the loop fire-and-forget.
        """
        prebuilt = self._try_build_args_sync(args, kwargs)
        with self.submission_lock:
            q = self.actor_queues.get(actor_id)
            new_q = q is None
            if new_q:
                q = ActorSubmitQueue(actor_id, self.submission_lock)
                self.actor_queues[actor_id] = q
            seq_no = q.next_seq()
            task_id = TaskID.for_actor_task(self.job_id, actor_id, seq_no,
                                            q.epoch)
            spec = TaskSpec(
                task_id=task_id, job_id=self.job_id, name=method_name,
                args=[], num_returns=num_returns,
                owner_address=self.address, owner_worker_id=self.worker_id,
                actor_id=actor_id, method_name=method_name, seq_no=seq_no,
                max_retries=max_task_retries,
                concurrency_group=concurrency_group,
                is_generator=is_generator,
            )
            self._stamp_trace(spec)
            q.inflight[seq_no] = spec
            refs: List[ObjectRef] = []
            returns: List[ObjectID] = []
            for i in range(num_returns):
                oid = ObjectID.for_task_return(task_id, i)
                self.owned[oid] = OwnedObject(object_id=oid)
                returns.append(oid)
                refs.append(ObjectRef(oid, self.address))
            if is_generator:
                self.generator_streams[task_id] = GeneratorStream(task_id,
                                                                  spec=spec)
            self.pending_tasks[task_id] = PendingTask(
                spec=spec, retries_left=max_task_retries, returns=returns)
        self._stamp_phase(task_id, PH_SUBMITTED)
        self._post_to_loop(
            self._post_threadsafe_actor_submit, q, spec, args, kwargs,
            prebuilt, new_q)
        if is_generator:
            from ray_tpu._private.object_ref import ObjectRefGenerator
            return [ObjectRefGenerator(task_id, self)]
        return refs

    def _post_threadsafe_actor_submit(self, q, spec, args, kwargs, prebuilt,
                                      new_q):
        if new_q:
            asyncio.ensure_future(self._populate_actor_queue(q))
        if (prebuilt is not None and q.state == "ALIVE"
                and not spec.is_generator):
            # Fast path: args already serialized, actor live — enqueue the
            # push directly with NO per-task coroutine; the batch flusher
            # dispatches the reply. Failures fall back to the retry loop.
            pt = self.pending_tasks.get(spec.task_id)
            if pt is None:
                self._return_handoff_credits(prebuilt[3])
                return  # cancelled before dispatch
            task_args, kw_names, pin_refs, credits = prebuilt
            spec.args = task_args
            spec.kwarg_names = tuple(kw_names)
            pt.arg_refs = self._pin_args(spec, pin_refs)
            pt.arg_credits = credits
            self._enqueue_actor_push(q, spec, None)
            return
        asyncio.ensure_future(
            self._finish_actor_task_submission(q, spec, args, kwargs,
                                               prebuilt))

    async def _finish_actor_task_submission(self, q: "ActorSubmitQueue",
                                            spec: TaskSpec, args, kwargs,
                                            prebuilt=None):
        try:
            task_args, kw_names, pin_refs, credits = (
                prebuilt if prebuilt is not None
                else await self._build_args(args, kwargs))
        except Exception as e:
            # Fail the caller's refs, but the reserved seq number MUST still
            # reach the actor: the receiver gates task start on contiguous
            # seq numbers, so a silent gap would hang every later call from
            # this caller. Send a no-op marker occupying the slot.
            self._complete_task_error(spec, e, retry=False)
            spec.method_name = SEQ_SKIP_METHOD
            spec.args = []
            spec.kwarg_names = ()
            await self._submit_actor_task(q, spec)
            return
        if spec.task_id not in self.pending_tasks:
            self._return_handoff_credits(credits)
            return  # cancelled before dispatch
        spec.args = task_args
        spec.kwarg_names = tuple(kw_names)
        pt = self.pending_tasks[spec.task_id]
        pt.arg_refs = self._pin_args(spec, pin_refs)
        pt.arg_credits = credits
        await self._submit_actor_task(q, spec)

    def _ensure_actor_queue(self, actor_id: ActorID) -> ActorSubmitQueue:
        with self.submission_lock:
            q = self.actor_queues.get(actor_id)
            if q is None:
                q = ActorSubmitQueue(actor_id, self.submission_lock)
                self.actor_queues[actor_id] = q
                asyncio.ensure_future(self._populate_actor_queue(q))
        return q

    async def _populate_actor_queue(self, q: ActorSubmitQueue):
        last_err = None
        for attempt in range(3):
            try:
                info: Optional[ActorInfo] = await self.gcs.request(
                    "get_actor_info", {"actor_id": q.actor_id})
            except Exception as e:
                last_err = e
                await asyncio.sleep(0.5 * (attempt + 1))
                continue
            if info is not None and q.state not in ("ALIVE", "DEAD"):
                # Don't clobber a state already delivered by pubsub.
                if info.state == ACTOR_ALIVE:
                    q.set_state("ALIVE", info.address)
                elif info.state == ACTOR_DEAD:
                    q.set_state("DEAD", reason=info.death_cause)
            return
        # GCS unreachable: fail queued tasks instead of hanging forever.
        if q.state not in ("ALIVE", "DEAD"):
            q.set_state("DEAD",
                        reason=f"could not resolve actor state: {last_err!r}")

    async def _connect_actor_queue(self, actor_id: ActorID) -> ActorSubmitQueue:
        q = self._ensure_actor_queue(actor_id)
        return q

    async def _submit_actor_task(self, q: ActorSubmitQueue, spec: TaskSpec):
        try:
            while True:
                if q.state == "DEAD":
                    if spec.method_name == SEQ_SKIP_METHOD:
                        # Marker's task already completed with its REAL
                        # error; completing again would overwrite it with
                        # ActorDiedError. A dead actor has no seq stream
                        # left to keep contiguous — just drop the marker.
                        return
                    self._complete_task_error(
                        spec, exc.ActorDiedError(q.actor_id, q.death_reason,
                                                 preempted=q.preempted),
                        retry=False)
                    return
                if q.state != "ALIVE":
                    await q.wait_for_change()
                    continue
                address = q.address
                epoch = q.epoch
                try:
                    reply = await self._push_actor_task_batched(q, spec)
                except rpc.RpcError:
                    # Actor worker connection failed; wait for GCS verdict
                    # (restart or death) then retry/fail.
                    if q.address == address and q.epoch == epoch:
                        q.set_state("RESTARTING")
                    if spec.method_name == SEQ_SKIP_METHOD:
                        # The marker's task is already completed (it has no
                        # pending entry) but the slot it fills is load-
                        # bearing: dropping it would hang every later call
                        # from this caller. Keep retrying until the actor
                        # state resolves.
                        await q.wait_for_change()
                        continue
                    pt = self.pending_tasks.get(spec.task_id)
                    if pt is None:
                        return
                    if q.preempted or pt.retries_left != 0:
                        # Drain/preemption-caused restarts retry for free;
                        # everything else consumes max_task_retries.
                        if not q.preempted and pt.retries_left > 0:
                            pt.retries_left -= 1
                        await q.wait_for_change()
                        continue
                    self._complete_task_error(
                        spec, exc.ActorDiedError(
                            q.actor_id, "actor worker died mid-call",
                            preempted=q.preempted),
                        retry=False)
                    return
                if spec.method_name != SEQ_SKIP_METHOD:
                    self._handle_task_reply(spec, reply, "")
                return
        finally:
            q.inflight.pop(spec.seq_no, None)

    # Max specs per push_actor_tasks frame: bounds reply latency for the
    # earliest task in a burst and keeps frames well under _MAX_MSG.
    ACTOR_PUSH_BATCH = 256

    def _enqueue_actor_push(self, q: ActorSubmitQueue, spec: TaskSpec,
                            fut: Optional[asyncio.Future]):
        """Append one push to the queue's outbox and schedule the flusher.

        fut=None marks a fast-path entry: the flusher dispatches the reply
        straight into _handle_task_reply (no per-task coroutine); failures
        re-enter the _submit_actor_task retry loop.
        """
        q.outbox.append((spec, fut, q.epoch))
        if not q.flush_scheduled:
            q.flush_scheduled = True
            asyncio.ensure_future(self._flush_actor_outbox(q))

    async def _push_actor_task_batched(self, q: ActorSubmitQueue,
                                       spec: TaskSpec) -> dict:
        """Queue one actor-task push; specs appended within the same loop
        tick coalesce into a single push_actor_tasks RPC (one pickle, one
        frame, one handler on the far side). Returns this spec's reply or
        raises rpc.RpcError like a direct request would."""
        fut = asyncio.get_running_loop().create_future()
        self._enqueue_actor_push(q, spec, fut)
        return await fut

    def _bounce_push(self, q: ActorSubmitQueue, spec: TaskSpec,
                     fut: Optional[asyncio.Future], err: Exception,
                     attempted: bool = False):
        """Fail one outbox entry: slow-path futures get the exception (their
        retry loop handles it); fast-path entries re-enter the retry loop.

        attempted=True means the push RPC may have REACHED the worker (the
        task may have executed): re-pushing then consumes one of the task's
        retries, and a task with max_task_retries=0 must fail instead of
        risking double execution (at-most-once; reference:
        direct_actor_task_submitter.h resend semantics)."""
        if fut is not None:
            if not fut.done():
                fut.set_exception(err)
            return
        # q.preempted relaxes at-most-once to at-least-once: a drained
        # actor's in-flight calls re-push to the migrated instance even at
        # max_task_retries=0 (same tradeoff as the plain-task path — the
        # alternative is failing every preemption for at-most-once users).
        if attempted and not q.preempted:
            pt = self.pending_tasks.get(spec.task_id)
            if pt is None:
                q.inflight.pop(spec.seq_no, None)
                return
            if pt.retries_left == 0:
                q.inflight.pop(spec.seq_no, None)
                self._complete_task_error(
                    spec, exc.ActorDiedError(
                        q.actor_id, "actor worker died mid-call"),
                    retry=False)
                return
            if pt.retries_left > 0:
                pt.retries_left -= 1
        asyncio.ensure_future(self._submit_actor_task(q, spec))

    async def _flush_actor_outbox(self, q: ActorSubmitQueue):
        q.flush_scheduled = False
        batch = q.outbox[:self.ACTOR_PUSH_BATCH]
        del q.outbox[:self.ACTOR_PUSH_BATCH]
        if not batch:
            return
        if q.outbox and not q.flush_scheduled:
            q.flush_scheduled = True
            asyncio.ensure_future(self._flush_actor_outbox(q))
        # Specs enqueued before a restart renumbering must not reach the
        # fresh worker with stale seq numbers: bounce them back to the
        # retry loop in _submit_actor_task.
        live = []
        for spec, fut, epoch in batch:
            if epoch != q.epoch or q.state != "ALIVE":
                self._bounce_push(q, spec, fut, rpc.ConnectionLost(
                    "actor restarted before push"))
            else:
                live.append((spec, fut))
        if not live:
            return
        address = q.address
        epoch = q.epoch
        record = self.config.task_events_enabled
        if record:
            self._observe_batch_size("actor", len(live))
            t_dispatch = time.time()
        for spec, _fut in live:
            # Shipping: the receiver's arg deserialization consumes the
            # handoff credits from here on.
            pt = self.pending_tasks.get(spec.task_id)
            if pt is not None:
                pt.arg_credits = []
                if record:
                    ph = pt.phases
                    if ph is None:
                        ph = pt.phases = [None] * RECORD_LEN
                    ph[PH_DISPATCHED] = t_dispatch
        try:
            if len(live) == 1:
                push_payload: dict = {"spec": live[0][0]}
                push_method = "push_actor_task"
            else:
                push_payload = {"specs": wire_spec_batch(
                    [s for s, _ in live])}
                push_method = "push_actor_tasks"
            if not record:
                push_payload["ph"] = 0  # executor skips its stamps too
            replies = await self.clients.request(
                address, push_method, push_payload, timeout=None,
                retry_once=False)
            if len(live) == 1:
                replies = [replies]
        except Exception as e:  # noqa: BLE001 — fan the failure out
            err = e if isinstance(e, rpc.RpcError) else rpc.RpcError(str(e))
            conn_lost = isinstance(e, rpc.ConnectionLost)
            if conn_lost and q.address == address \
                    and q.epoch == epoch and q.state == "ALIVE":
                # Connection-level failure with no fresh state from the GCS
                # yet: park the queue so retry loops wait for the verdict.
                q.set_state("RESTARTING")
            if not conn_lost and len(live) > 1:
                # Frame-level reply failure: one spec's reply can poison
                # the whole batch (ADVICE r4). Isolate by re-pushing each
                # spec as its OWN RPC so only the culprit fails. The tasks
                # may have EXECUTED (only the reply was lost), so a
                # re-push is a re-execution: it must honor at-most-once —
                # specs with no retries left fail instead (their seq slot
                # is filled with a SEQ_SKIP marker to keep batch-mates
                # and later calls live). The seq gate tolerates replayed
                # seqs (cursor never regresses).
                repush: List[tuple] = []
                for spec, fut in live:
                    if fut is not None:
                        # Slow path: its retry loop owns the accounting.
                        self._bounce_push(q, spec, fut, err, attempted=True)
                        continue
                    pt = self.pending_tasks.get(spec.task_id)
                    if pt is None:
                        q.inflight.pop(spec.seq_no, None)
                        continue
                    if pt.retries_left == 0 and not q.preempted:
                        self._fail_and_fill_seq(q, spec, exc.ActorDiedError(
                            q.actor_id,
                            "reply lost for a batched actor call "
                            "(max_task_retries=0 forbids re-execution)"))
                        continue
                    if pt.retries_left > 0 and not q.preempted:
                        pt.retries_left -= 1
                    repush.append((spec, fut))
                if repush:
                    # ONE coroutine, seq order: concurrent re-pushes of
                    # replayed seqs would bypass the receiver's start gate
                    # (replays are <= the cursor) and could interleave out
                    # of order on a serial actor.
                    repush.sort(key=lambda it: it[0].seq_no)
                    asyncio.ensure_future(self._repush_sequentially(
                        q, repush, address, epoch))
                return
            for spec, fut in live:
                if fut is None and not conn_lost:
                    # Non-connection failure (e.g. a reply the handler could
                    # not produce): deterministic — retrying would hot-loop.
                    self._fail_and_fill_seq(q, spec, err)
                else:
                    # The request was sent: the worker may have executed it.
                    self._bounce_push(q, spec, fut, err, attempted=True)
            return
        for (spec, fut), reply in zip(live, replies):
            if fut is not None:
                if not fut.done():
                    fut.set_result(reply)
                continue
            # Fast path: complete the task inline.
            q.inflight.pop(spec.seq_no, None)
            try:
                self._handle_task_reply(spec, reply, "")
            except Exception:
                logger.exception("actor task reply dispatch failed")

    def _fail_and_fill_seq(self, q: ActorSubmitQueue, spec: TaskSpec,
                           error: Exception):
        """Fail one actor task AND fill its reserved seq slot.

        The receiver gates task start on contiguous per-caller seq
        numbers: completing a spec with an error without its seq ever
        reaching the actor leaves a gap that hangs every later call from
        this caller. Ship a SEQ_SKIP no-op marker occupying the slot
        (same invariant as the failed-arg-serialization path). If the
        worker already saw the original seq, the marker replay is benign
        (the seq cursor never regresses)."""
        q.inflight.pop(spec.seq_no, None)
        self._complete_task_error(spec, error, retry=False)
        marker = copy.copy(spec)
        marker.method_name = SEQ_SKIP_METHOD
        marker.args = []
        marker.kwarg_names = ()
        q.inflight[marker.seq_no] = marker
        asyncio.ensure_future(self._submit_actor_task(q, marker))

    async def _repush_sequentially(self, q: ActorSubmitQueue, items,
                                   address: str, epoch: int):
        for spec, fut in items:
            await self._repush_single(q, spec, fut, address, epoch)

    async def _repush_single(self, q: ActorSubmitQueue, spec: TaskSpec,
                             fut: Optional[asyncio.Future], address: str,
                             epoch: int):
        """Re-push ONE spec of a failed batch frame as its own RPC.

        Isolation fallback (ADVICE r4): only the spec whose reply genuinely
        cannot be produced fails; its batch-mates complete normally. The
        caller has already consumed one retry (the original frame may have
        executed)."""
        try:
            reply = await self.clients.request(
                address, "push_actor_task", {"spec": spec}, timeout=None,
                retry_once=False)
        except Exception as e:  # noqa: BLE001
            err = e if isinstance(e, rpc.RpcError) else rpc.RpcError(str(e))
            conn_lost = isinstance(e, rpc.ConnectionLost)
            if conn_lost and q.address == address \
                    and q.epoch == epoch and q.state == "ALIVE":
                q.set_state("RESTARTING")
            if fut is None and not conn_lost:
                # Deterministic failure even alone: the reply for THIS
                # spec cannot be produced. Fail it but keep the caller's
                # seq stream contiguous.
                self._fail_and_fill_seq(q, spec, err)
            else:
                self._bounce_push(q, spec, fut, err, attempted=True)
            return
        if fut is not None:
            if not fut.done():
                fut.set_result(reply)
            return
        q.inflight.pop(spec.seq_no, None)
        try:
            self._handle_task_reply(spec, reply, "")
        except Exception:
            logger.exception("actor task reply dispatch failed")

    async def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        reg = self._actor_registrations.get(actor_id)
        if reg is not None and not reg.done():
            # The create's registration is still in flight (anonymous
            # creates don't await it): a kill racing ahead of it would
            # no-op at the GCS and the actor would be created anyway.
            try:
                await asyncio.wait_for(asyncio.shield(reg), 30)
            except Exception:  # noqa: BLE001 — kill proceeds regardless
                pass
        await self.gcs.request("kill_actor", {"actor_id": actor_id,
                                              "no_restart": no_restart})

    # ---- compiled-DAG lease pinning (dag/compiled.py) ----

    async def local_node_id(self):
        """This process's hosting node id. Workers know it from their
        environment; a driver resolves it ONCE by matching the raylet it
        dialed (compiled DAGs use it to decide which channel edges can
        be same-node shm rings)."""
        if self.node_id is not None:
            return self.node_id
        try:
            nodes = await self.gcs.request("get_all_nodes", {})
        except rpc.RpcError:
            return None
        for n in nodes:
            if n.address == self.raylet_address:
                self.node_id = n.node_id
                break
        return self.node_id

    async def _wait_actor_alive(self, actor_id: ActorID,
                                timeout_s: float) -> "ActorInfo":
        """Poll until the actor is ALIVE with a known placement — a
        compiled DAG pins leases against live workers only."""
        deadline = time.time() + timeout_s
        while True:
            info = await self.gcs.request("get_actor_info",
                                          {"actor_id": actor_id})
            if info is not None:
                if info.state == ACTOR_ALIVE and info.node_id is not None:
                    return info
                if info.state == ACTOR_DEAD:
                    raise exc.ActorDiedError(
                        actor_id, info.death_cause
                        or "died before DAG compile finished")
            if time.time() > deadline:
                raise exc.GetTimeoutError(
                    f"actor {actor_id.hex()[:12]} not ALIVE within "
                    f"{timeout_s}s (state="
                    f"{getattr(info, 'state', 'unknown')})")
            await asyncio.sleep(0.05)

    async def dag_pin_actors(self, dag_id: str, actor_ids: list,
                             timeout_s: float = 60.0) -> dict:
        """Resolve every participant's placement and pin its worker's
        lease at the hosting raylet for the DAG's lifetime. Returns
        {actor_id: {node_id, worker_id, raylet}}; dag_release() undoes
        the pins. Placement waits and per-raylet pins run CONCURRENTLY
        (compile latency stays O(slowest actor), not O(actors)); a
        partial failure rolls back every raylet already pinned — a
        half-pinned DAG would leak OOM/reaper-exempt leases forever."""
        async def _place(aid):
            info = await self._wait_actor_alive(aid, timeout_s)
            node = await self.gcs.request("get_node_address",
                                          {"node_id": info.node_id})
            if not node or not node.get("alive"):
                raise exc.ActorUnavailableError(
                    f"actor {aid.hex()[:12]}'s node is not alive")
            return aid, {"node_id": info.node_id,
                         "worker_id": info.worker_id,
                         "raylet": node["address"]}

        placements = dict(await asyncio.gather(
            *[_place(aid) for aid in actor_ids]))
        by_addr: Dict[str, list] = {}
        for aid, p in placements.items():
            by_addr.setdefault(p["raylet"], []).append(aid)
        results = await asyncio.gather(
            *[self.clients.request(addr, "dag_pin_workers",
                                   {"dag_id": dag_id, "actor_ids": aids})
              for addr, aids in by_addr.items()],
            return_exceptions=True)
        failed = next((r for r in results if isinstance(r, BaseException)),
                      None)
        if failed is not None:
            await self.dag_release(dag_id, list(by_addr))
            raise failed
        return placements

    async def dag_register(self, dag_id: str, node_ids: list):
        """(Re)register a compiled DAG's CURRENT participant-node
        footprint in the GCS drain index (keyed upsert) — a (gang-)drain
        notice resolves the affected DAGs there and stamps their ids
        into the event. The caller (CompiledDAG._pin) passes the pruned
        footprint so replaced participants' old nodes drop out."""
        try:
            await self.gcs.request("dag_register", {
                "dag_id": dag_id,
                "node_ids": sorted(set(node_ids), key=lambda n: n.hex())})
        except rpc.RpcError:
            pass  # best-effort index: drivers also match by node id

    async def dag_release(self, dag_id: str, raylet_addrs: list,
                          unregister: bool = False) -> list:
        """Release every lease `dag_id` pinned at `raylet_addrs`;
        returns the released worker ids (hex). A PARTIAL release
        (recovery handing off a draining/stale raylet) keeps the GCS
        drain-index entry; `unregister=True` (teardown / failed
        recovery — the DAG is gone for good) drops it. A vanished
        raylet released implicitly — its leases died with it."""
        released: list = []
        for addr in raylet_addrs:
            try:
                released.extend(await self.clients.request(
                    addr, "dag_release_workers", {"dag_id": dag_id}))
            except rpc.RpcError:
                pass
        if unregister:
            try:
                await self.gcs.request("dag_unregister",
                                       {"dag_id": dag_id})
            except rpc.RpcError:
                pass
        return released

    async def dag_lease_accounting(self, raylet_addrs: list) -> dict:
        """{dag_id: [worker hexes]} merged across `raylet_addrs` — the
        accounting surface teardown tests assert empties out."""
        merged: Dict[str, list] = {}
        for addr in raylet_addrs:
            try:
                acct = await self.clients.request(
                    addr, "dag_lease_accounting", {})
            except rpc.RpcError:
                continue
            for dag_id, workers in acct.items():
                merged.setdefault(dag_id, []).extend(workers)
        return merged

    async def get_named_actor(self, name: str, namespace: str = ""):
        info: Optional[ActorInfo] = await self.gcs.request(
            "get_named_actor", {"name": name, "namespace": namespace})
        if info is None or info.state == ACTOR_DEAD:
            raise ValueError(f"named actor '{name}' not found")
        with self.submission_lock:
            q = self.actor_queues.get(info.actor_id)
            if q is None:
                q = ActorSubmitQueue(info.actor_id, self.submission_lock)
                if info.state == ACTOR_ALIVE:
                    q.set_state("ALIVE", info.address)
                self.actor_queues[info.actor_id] = q
        return info

    # ==================================================================
    # Task execution (worker mode)
    # ==================================================================

    async def _resolve_task_args(self, spec: TaskSpec) -> Tuple[list, dict]:
        kw_names = spec.kwarg_names
        values = []
        for arg in spec.args:
            if arg.kind == ARG_INLINE:
                values.append(self.serialization.deserialize(arg.data))
            else:
                ref = ObjectRef(arg.object_id, arg.owner_address,
                                skip_refcount=True)
                value, is_exc_ = await self._resolve_object(ref, None)
                if is_exc_:
                    raise _DependencyError(value)
                values.append(value)
        if kw_names:
            n_pos = len(values) - len(kw_names)
            return values[:n_pos], dict(zip(kw_names, values[n_pos:]))
        return values, {}

    def _resolve_inline_args(self, spec: TaskSpec) -> Tuple[list, dict]:
        """Synchronous arg resolution for ALL-INLINE specs: no coroutine
        per task on the executor's batch hot path (inline deserialization
        never blocks)."""
        deser = self.serialization.deserialize
        values = [deser(a.data) for a in spec.args]
        kw_names = spec.kwarg_names
        if kw_names:
            n_pos = len(values) - len(kw_names)
            return values[:n_pos], dict(zip(kw_names, values[n_pos:]))
        return values, {}

    def _serialize_return(self, value: Any, is_exception: bool = False
                          ) -> tuple:
        """Flat return record (inline_bytes|None, large_ser|None, is_exc);
        a SerializedObject in slot 1 means the value needs a plasma put
        (the caller replaces it with the storing raylet's address)."""
        limit = self.plane_threshold
        data = self.serialization.serialize_inline(value, limit)
        if data is not None:
            return (data, None, is_exception)
        ser = self.serialization.serialize(value)
        if ser.total_size <= limit:
            return (ser.to_bytes(), None, is_exception)
        return (None, ser, is_exception)

    async def _store_returns(self, spec: TaskSpec, values: List[Any],
                             is_exception: bool = False) -> list:
        out = []
        for i, v in enumerate(values):
            r = self._serialize_return(v, is_exception)
            ser = r[1]
            if ser is not None:
                oid = ObjectID.for_task_return(spec.task_id, i)
                meta = META_EXCEPTION if is_exception else b""
                await self.store.put(oid.binary(), ser, metadata=meta,
                                     owner_address=spec.owner_address)
                r = (None, self.raylet_address, is_exception)
            out.append(r)
        return out

    @rpc.non_idempotent
    async def _rpc_push_task(self, conn, payload):
        async with self._task_exec_lock:  # pipelined pushes run one-by-one
            return await self._push_task_locked(payload)

    _CANCELLED = object()  # run_all sentinel: task cancelled pre-start

    async def _run_sync_jobs(self, jobs: list, replies: list):
        """Execute (idx, spec, fn, args, kwargs, phases) jobs in ONE pool
        job and fill replies[idx] with the single-task reply envelopes.
        Shared by the plain-task and actor batch paths — keep their
        semantics in one place. Cancellation is re-checked immediately
        before each task runs (a cancel mid-batch skips everything not yet
        started; the currently running sync call is not interruptible,
        same as a pool future that already started). `phases` (dict or
        None) collects the flight recorder's exec_start/exec_end stamps
        per task even though the batch shares one pool hop."""

        def run_all():
            out = []
            for _i, _spec, fn, args, kwargs, _ph in jobs:
                if _spec.task_id in self._cancelled_tasks:
                    out.append((self._CANCELLED, None))
                    continue
                self.current_task_id = _spec.task_id
                if _ph is not None:
                    _ph[PH_EXEC_START] = time.time()
                try:
                    out.append((True, fn(*args, **kwargs)))
                except BaseException as e:  # noqa: BLE001 — per-task fault
                    out.append((False, (e, traceback.format_exc())))
                if _ph is not None:
                    _ph[PH_EXEC_END] = time.time()
            return out

        results = await self._run_in_pool(run_all)
        for (i, spec, _f, _a, _kw, ph), (ok, res) in zip(jobs, results):
            self.current_task_id = spec.task_id
            try:
                if ok is self._CANCELLED:
                    replies[i] = {"cancelled": True}
                elif ok:
                    values = self._split_returns(res, spec.num_returns)
                    returns = await self._store_returns(spec, values)
                    if ph is not None:
                        ph[PH_RESULT_PUT] = time.time()
                    replies[i] = (returns, ph)
                else:
                    e, tb_str = res
                    err = exc.TaskError(e, tb_str, spec.task_id, os.getpid())
                    returns = await self._store_returns(
                        spec, [err] * spec.num_returns, is_exception=True)
                    replies[i] = self._app_error_envelope(err, returns)
                    if ph is not None:
                        replies[i]["phases"] = ph
            except Exception as e:  # noqa: BLE001 — e.g. bad num_returns
                replies[i] = {"system_error": f"{type(e).__name__}: {e}"}
            finally:
                # Drop a cancel marker once it has been acted on (or raced
                # a task that already started).
                self._cancelled_tasks.discard(spec.task_id)
        self.current_task_id = None

    def _app_error_envelope(self, err, returns) -> dict:
        """Reply envelope for an application error, guaranteed picklable.

        The rpc layer pickles replies with plain pickle: an unpicklable
        user exception would fail the WHOLE reply (and for batched frames,
        poison every batch-mate — ADVICE r4). Probe the error alone
        (returns entries are already serialized bytes) and degrade to a
        picklable placeholder that still carries `app_error` so the
        caller's retry_exceptions handling keeps working."""
        import pickle as _pickle
        try:
            _pickle.dumps(err, protocol=5)
        except Exception as e:  # noqa: BLE001
            err = exc.RayTpuError(
                f"unpicklable task error {type(getattr(err, 'cause', err)).__name__}: "
                f"{err}"[:4096])
        return {"app_error": err, "returns": returns}

    @rpc.non_idempotent
    async def _rpc_push_task_batch(self, conn, payload):
        """Execute a batch sequentially; one reply list for all. Per-spec
        isolation: an escaping system error fails that spec, not the
        batch (a batch-wide RPC failure would make the submitter re-run
        every completed task).

        Contiguous plain-sync specs (no generator/async/trace) run in ONE
        executor-pool job: the per-call pool hop (queue ops + self-pipe
        wakeup) is the dominant worker-side cost for tiny tasks."""
        specs = payload["specs"]
        replies: list = [None] * len(specs)
        sync_jobs: list = []  # (reply idx, spec, func, args, kwargs)

        async def flush_jobs():
            if not sync_jobs:
                return
            jobs = list(sync_jobs)
            sync_jobs.clear()
            await self._run_sync_jobs(jobs, replies)

        # Applying a spec's runtime env mutates PROCESS-WIDE state (chdir,
        # sys.path, pip venv): queued sync jobs from earlier specs must run
        # BEFORE a different env is applied, or they execute under the
        # later spec's env (ADVICE r4 — caller-side scheduling-class
        # homogeneity makes mixed-env batches unlikely, but the handler
        # must enforce it itself).
        current_env_key: Any = None

        want_ph = payload.get("ph", 1)
        fn_cache = self._function_cache
        async with self._task_exec_lock:
            for i, spec in enumerate(specs):
                ph = self._new_exec_phases(want_ph)
                # Steady-state fast path: function cached, no runtime env
                # (and none pending from an earlier spec), all-inline args
                # — zero coroutines per spec.
                func = (fn_cache.get(spec.function_id)
                        if not spec.runtime_env and current_env_key is None
                        else None)
                if func is not None \
                        and not any(a.kind != ARG_INLINE
                                    for a in spec.args):
                    try:
                        args, kwargs = self._resolve_inline_args(spec)
                    except Exception as e:  # noqa: BLE001
                        replies[i] = {
                            "system_error": f"{type(e).__name__}: {e}"}
                        continue
                else:
                    # Mirror _push_task_locked's prep + error envelope.
                    try:
                        env_key = (repr(sorted(spec.runtime_env.items()))
                                   if spec.runtime_env else None)
                        if env_key != current_env_key:
                            await flush_jobs()
                            current_env_key = env_key
                        await self._ensure_runtime_env(spec.runtime_env)
                        func = await self._load_function(spec.function_id)
                        if any(a.kind != ARG_INLINE for a in spec.args):
                            # Bounded: a ref arg that can only become
                            # ready via THIS batch's reply (a submitter
                            # bug — _take_batch forbids it) must degrade
                            # to a retryable error, not wedge the
                            # worker's exec lock forever. Inline args
                            # never block: skip the wait_for Task per
                            # spec.
                            args, kwargs = await asyncio.wait_for(
                                self._resolve_task_args(spec),
                                timeout=self.config.worker_lease_timeout_s)
                        else:
                            args, kwargs = await self._resolve_task_args(
                                spec)
                    except _DependencyError as e:
                        replies[i] = self._app_error_envelope(e.error, None)
                        continue
                    except exc.RuntimeEnvSetupError as e:
                        err = exc.TaskError(e, str(e), spec.task_id,
                                            os.getpid())
                        returns = await self._store_returns(
                            spec, [err] * spec.num_returns,
                            is_exception=True)
                        replies[i] = self._app_error_envelope(err, returns)
                        continue
                    except Exception as e:  # noqa: BLE001
                        replies[i] = {
                            "system_error": f"{type(e).__name__}: {e}"}
                        continue
                if ph is not None:
                    ph[PH_ARGS_READY] = time.time()
                if spec.task_id in self._cancelled_tasks:
                    self._cancelled_tasks.discard(spec.task_id)
                    replies[i] = {"cancelled": True}
                    continue
                if (spec.is_generator or asyncio.iscoroutinefunction(func)
                        or spec.trace_ctx is not None):
                    await flush_jobs()
                    try:
                        replies[i] = await self._push_task_locked(
                            {"spec": spec, "ph": want_ph})
                    except Exception as e:  # noqa: BLE001
                        replies[i] = {
                            "system_error": f"{type(e).__name__}: {e}"}
                    continue
                sync_jobs.append((i, spec, func, args, kwargs, ph))
            await flush_jobs()
        return replies


    def _new_exec_phases(self, want: int = 1) -> Optional[list]:
        """Executor-side flight-recorder record, stamped 'received' (None
        with events off). Shipped back inside the reply envelope under
        "phases"; the worker-id slot identifies this worker for the
        cross-process flow events in the timeline. `want` is the OWNER's
        recorder state (push payload "ph" key): an owner with events off
        turns the executor-side stamping off too, so the off-mode (and
        the bench's overhead delta) covers the whole pipeline, not just
        the owner half."""
        if not want or not self.config.task_events_enabled:
            return None
        ph = [None] * RECORD_LEN
        ph[PH_RECEIVED] = time.time()
        ph[IDX_WORKER] = self._worker_hex
        return ph

    async def _push_task_locked(self, payload):
        spec: TaskSpec = payload["spec"]
        self.current_task_id = spec.task_id
        ph = self._new_exec_phases(payload.get("ph", 1))
        try:
            await self._ensure_runtime_env(spec.runtime_env)
            func = await self._load_function(spec.function_id)
            args, kwargs = await self._resolve_task_args(spec)
        except _DependencyError as e:
            return self._app_error_envelope(e.error, None)
        except exc.RuntimeEnvSetupError as e:
            err = exc.TaskError(e, str(e), spec.task_id, os.getpid())
            returns = await self._store_returns(
                spec, [err] * spec.num_returns, is_exception=True)
            return self._app_error_envelope(err, returns)
        except Exception as e:  # noqa: BLE001
            return {"system_error": f"{type(e).__name__}: {e}"}
        if ph is not None:
            ph[PH_ARGS_READY] = time.time()
        span = self._maybe_start_span(spec)
        try:
            if spec.task_id in self._cancelled_tasks:
                self._cancelled_tasks.discard(spec.task_id)
                return {"cancelled": True}
            loop = asyncio.get_running_loop()
            if spec.is_generator:
                return await self._execute_generator_task(spec, func, args,
                                                          kwargs)
            if ph is not None:
                ph[PH_EXEC_START] = time.time()
            if asyncio.iscoroutinefunction(func):
                task = asyncio.ensure_future(func(*args, **kwargs))
                self._running_tasks[spec.task_id] = task
                result = await task
            else:
                fut = self._run_in_pool(func, *args, **kwargs)
                self._running_tasks[spec.task_id] = fut
                result = await fut
            if ph is not None:
                ph[PH_EXEC_END] = time.time()
            values = self._split_returns(result, spec.num_returns)
            returns = await self._store_returns(spec, values)
            if ph is not None:
                ph[PH_RESULT_PUT] = time.time()
            return (returns, ph)
        except asyncio.CancelledError:
            return {"cancelled": True}
        except Exception as e:  # noqa: BLE001
            import os as _os
            err = exc.TaskError(e, traceback.format_exc(), spec.task_id,
                                _os.getpid())
            returns = await self._store_returns(
                spec, [err] * spec.num_returns, is_exception=True)
            envelope = self._app_error_envelope(err, returns)
            if ph is not None:
                envelope["phases"] = ph
            return envelope
        finally:
            self._finish_span(span)
            self._running_tasks.pop(spec.task_id, None)
            self.current_task_id = None

    @staticmethod
    def _stamp_trace(spec: TaskSpec):
        """Attach the caller's trace context to an outgoing spec (no-op
        unless a span is active or this process enabled tracing)."""
        ctx = _tracing.current_context()
        if ctx is not None:
            spec.trace_ctx = ctx

    def _run_in_pool(self, fn, *args, **kwargs):
        """User code on the exec pool WITH contextvars (run_in_executor
        alone would orphan child spans and any submission context)."""
        import contextvars
        ctx = contextvars.copy_context()
        return asyncio.get_running_loop().run_in_executor(
            self._exec_pool, lambda: ctx.run(fn, *args, **kwargs))

    def _maybe_start_span(self, spec: TaskSpec):
        # Spans record exactly when the submitter traced this task.
        if spec.trace_ctx is None:
            return None
        return _tracing.start_span(
            spec.name or spec.method_name or spec.function_id,
            spec.trace_ctx, spec.task_id.hex())

    def _finish_span(self, span):
        if span is None:
            return
        self._span_events.append(_tracing.end_span(span))
        if len(self._span_events) > 20000:
            del self._span_events[:10000]  # exporter unreachable: window

    async def _execute_generator_task(self, spec: TaskSpec, func, args,
                                      kwargs) -> dict:
        """Streamed execution: each yielded value ships to the owner as its
        own return object the moment it is produced (reference:
        num_returns='streaming', task_manager.h ObjectRefStream)."""
        import inspect as _inspect
        loop = asyncio.get_running_loop()
        index = 0
        try:
            owner = await self.clients.get(spec.owner_address)
        except rpc.RpcError:
            return {"system_error": "generator owner unreachable"}

        async def emit(value, is_exception=False):
            nonlocal index
            r = self._serialize_return(value, is_exception)
            if r[1] is not None:
                ser = r[1]
                oid = ObjectID.for_task_return(spec.task_id, index)
                meta = META_EXCEPTION if is_exception else b""
                await self.store.put(oid.binary(), ser, metadata=meta,
                                     owner_address=spec.owner_address)
                r = (None, self.raylet_address, is_exception)
            await owner.notify("generator_item", {
                "task_id": spec.task_id, "index": index, "ret": r,
                "exec_raylet": self.raylet_address,
                "exec_worker": self.address})
            index += 1
            # End the tick: an async generator that never truly suspends
            # (e.g. wrapping a sync generator) would otherwise run to
            # exhaustion inside ONE tick, so the write-coalescer holds every
            # item after the first until the end — the opposite of
            # streaming. sleep(0) lets the scheduled flush run per item.
            await asyncio.sleep(0)

        def _released() -> bool:
            # Consumer dropped the stream (release_generator sent a
            # cancel): stop producing.
            if spec.task_id in self._cancelled_tasks:
                self._cancelled_tasks.discard(spec.task_id)
                return True
            return False

        try:
            if _inspect.isasyncgenfunction(func):
                async for item in func(*args, **kwargs):
                    if _released():
                        return {"generator_done": index, "cancelled": True}
                    await emit(item)
            else:
                gen = func(*args, **kwargs)
                if not _inspect.isgenerator(gen):
                    raise TypeError(
                        f"num_returns='streaming' requires a generator "
                        f"function, got {type(gen)} from {spec.name}")

                def _next():
                    try:
                        return True, next(gen)
                    except StopIteration:
                        return False, None

                while True:
                    more, item = await loop.run_in_executor(self._exec_pool,
                                                            _next)
                    if not more:
                        break
                    if _released():
                        return {"generator_done": index, "cancelled": True}
                    await emit(item)
        except Exception as e:  # noqa: BLE001
            import os as _os
            err = exc.TaskError(e, traceback.format_exc(), spec.task_id,
                                _os.getpid())
            await emit(err, is_exception=True)
        return {"generator_done": index}

    @staticmethod
    def _split_returns(result: Any, num_returns: int) -> List[Any]:
        if num_returns == 1:
            return [result]
        if not isinstance(result, (tuple, list)) or len(result) != num_returns:
            raise ValueError(
                f"task declared num_returns={num_returns} but returned "
                f"{type(result)}")
        return list(result)

    @rpc.idempotent
    async def _rpc_cancel_task(self, conn, payload):
        task_id = payload["task_id"]
        running = self._running_tasks.get(task_id)
        if running is None:
            self._cancelled_tasks.add(task_id)
            return False
        running.cancel()
        return True

    # ---- actor execution ----

    @rpc.non_idempotent
    async def _rpc_instantiate_actor(self, conn, payload):
        spec: TaskSpec = payload["spec"]
        try:
            blob = payload.get("function_blob")
            if blob is not None and spec.function_id not in \
                    self._function_cache:
                # The raylet prefetched the (content-addressed) class and
                # shipped it along: skip the per-worker KV fetch a launch
                # storm would otherwise multiply by N.
                import pickle as _pickle
                self._function_cache[spec.function_id] = \
                    _pickle.loads(blob)
            await self._ensure_runtime_env(spec.runtime_env)
            cls = await self._load_function(spec.function_id)
            args, kwargs = await self._resolve_task_args(spec)
            loop = asyncio.get_running_loop()
            instance = await loop.run_in_executor(
                self._exec_pool, lambda: cls(*args, **kwargs))
        except Exception:
            # Application error in the constructor: report it as data, not
            # an RPC failure — the GCS must count it against max_restarts
            # instead of rescheduling forever.
            import traceback
            return {"app_error": traceback.format_exc()}
        # Compiled-DAG hook: every actor can host a channel loop without
        # the class opting in (reference: compiled_dag_node.py pins
        # internal executables onto participating actors).
        import types as _types
        from ray_tpu.dag.compiled import _dag_loop_method
        try:
            instance.__ray_tpu_dag_loop__ = _types.MethodType(
                _dag_loop_method, instance)
        except Exception:  # noqa: BLE001
            # __slots__ or validating __setattr__ (e.g. pydantic): the
            # actor works normally, it just can't host compiled DAGs.
            pass
        self.executing_actor = instance
        self.executing_actor_info = {
            "spec": spec, "max_concurrency": spec.max_concurrency,
            "is_async": spec.is_async_actor,
            "num_restarts": payload.get("num_restarts", 0),
        }
        self.current_actor_id = spec.actor_id
        self._actor_max_concurrency = max(1, spec.max_concurrency)
        self._actor_semaphore = asyncio.Semaphore(self._actor_max_concurrency)
        # Named concurrency groups: each gets an independent semaphore, so
        # e.g. an "io" group keeps serving while "compute" is saturated
        # (reference: concurrency_group_manager.h).
        self._group_semaphores = {
            name: asyncio.Semaphore(max(1, int(limit)))
            for name, limit in (spec.concurrency_groups or {}).items()}
        self._execute_out_of_order = spec.execute_out_of_order
        self._caller_next_seq = {}
        self._caller_buffer = {}
        return True

    @rpc.non_idempotent
    async def _rpc_push_actor_tasks(self, conn, payload):
        """Batched push: one frame of specs from one caller, replies as an
        aligned list. A plain serial actor (max_concurrency=1, sync
        methods, no groups) executes the whole batch in ONE executor-pool
        job — the per-call pool hop (queue ops + self-pipe wakeup, ~3
        epoll wakeups/call measured) is the dominant worker-side cost.
        Everything else runs concurrently via the per-spec path (the seq
        gate and semaphore impose the actual ordering)."""
        specs = payload["specs"]
        want_ph = payload.get("ph", 1)
        if self._can_batch_execute(specs):
            replies = await self._execute_actor_batch(specs, want_ph)
        else:
            replies = list(await asyncio.gather(*[
                self._rpc_push_actor_task(conn, {"spec": s, "ph": want_ph})
                for s in specs]))
        # Reply picklability is guaranteed per-entry at envelope-build time
        # (_app_error_envelope): one task's unpicklable error can no longer
        # poison the frame for its batch-mates (ADVICE r4).
        return replies

    def _gate_seq_entry(self, spec: TaskSpec):
        """Sync half of the per-caller in-order start gate: None when the
        spec may start NOW (the overwhelmingly common in-order case — no
        coroutine needed), else a future to await before calling
        _gate_seq_advance."""
        if getattr(self, "_execute_out_of_order", False):
            # Out-of-order mode: tasks start as they arrive (reference:
            # out_of_order_actor_scheduling_queue).
            return None
        caller = spec.owner_worker_id.binary()
        next_seq = self._caller_next_seq.setdefault(caller, 0)
        if spec.seq_no > next_seq:
            # Out-of-order arrival: buffer until predecessors START.
            buf = self._caller_buffer.setdefault(caller, {})
            fut = asyncio.get_running_loop().create_future()
            buf[spec.seq_no] = fut
            return fut
        return None

    def _gate_seq_advance(self, spec: TaskSpec):
        if getattr(self, "_execute_out_of_order", False):
            return
        caller = spec.owner_worker_id.binary()
        # max(): a REPLAYED seq (client re-push after a frame-level reply
        # failure — the task may have already run here) must not regress
        # the cursor, or every later seq buffers forever (liveness).
        self._caller_next_seq[caller] = max(
            self._caller_next_seq.get(caller, 0), spec.seq_no + 1)
        buf = self._caller_buffer.get(caller)
        if buf:
            nxt = buf.pop(spec.seq_no + 1, None)
            if nxt is not None and not nxt.done():
                nxt.set_result(None)

    async def _gate_actor_seq(self, spec: TaskSpec):
        """Per-caller in-order start gate (reference:
        actor_scheduling_queue.cc). Ordering gates task *start*, not
        completion: the cursor advances and the successor wakes before the
        task body runs, so async/concurrent actors interleave."""
        fut = self._gate_seq_entry(spec)
        if fut is not None:
            await fut
        self._gate_seq_advance(spec)

    def _can_batch_execute(self, specs) -> bool:
        if (self.executing_actor is None
                or getattr(self, "_execute_out_of_order", False)
                or getattr(self, "_actor_max_concurrency", 1) != 1):
            return False
        for spec in specs:
            if (spec.is_generator or spec.concurrency_group
                    or spec.trace_ctx is not None):
                return False
            # Only inline args: resolving an ObjectRef arg can yield to the
            # loop between the seq-gate and the semaphore acquire, letting a
            # later frame overtake this one on a serial actor. All-inline
            # resolution never yields, so gate order == execution order.
            if any(a.kind != ARG_INLINE for a in spec.args):
                return False
            if spec.method_name == SEQ_SKIP_METHOD:
                continue
            m = getattr(self.executing_actor, spec.method_name, None)
            if m is None or asyncio.iscoroutinefunction(m):
                return False
        return True

    async def _execute_actor_batch(self, specs, want_ph: int = 1) -> list:
        """Batch execution with single-push semantics: per-spec error
        envelopes (one task's failure must never fail — or wedge — the
        whole frame) and cancellation honored up to execution start."""
        replies: list = [None] * len(specs)
        jobs = []  # (reply index, spec, bound method, args, kwargs, phases)
        for i, spec in enumerate(specs):
            ph = self._new_exec_phases(want_ph)
            gate = self._gate_seq_entry(spec)
            if gate is not None:  # in-order arrivals never allocate a Task
                await gate
            self._gate_seq_advance(spec)
            if spec.method_name == SEQ_SKIP_METHOD:
                replies[i] = ((), None)
                continue
            if spec.task_id in self._cancelled_tasks:
                self._cancelled_tasks.discard(spec.task_id)
                replies[i] = {"cancelled": True}
                continue
            try:
                # _can_batch_execute guarantees all-inline args: resolve
                # synchronously, no coroutine per spec.
                args, kwargs = self._resolve_inline_args(spec)
            except Exception as e:  # noqa: BLE001
                replies[i] = {"system_error": f"{type(e).__name__}: {e}"}
                continue
            if ph is not None:
                ph[PH_ARGS_READY] = time.time()
            jobs.append((i, spec,
                         getattr(self.executing_actor, spec.method_name),
                         args, kwargs, ph))
        if not jobs:
            return replies
        async with self._actor_semaphore:
            await self._run_sync_jobs(jobs, replies)
        return replies

    @rpc.non_idempotent
    async def _rpc_push_actor_task(self, conn, payload):
        spec: TaskSpec = payload["spec"]
        if self.executing_actor is None:
            return {"system_error": "no actor instantiated on this worker"}
        await self._gate_actor_seq(spec)
        if spec.method_name == SEQ_SKIP_METHOD:
            # Seq-slot placeholder for a submission that failed caller-side
            # (e.g. unserializable args): ordering advanced, nothing to run.
            return ((), None)
        return await self._execute_actor_task(spec, payload.get("ph", 1))

    async def _execute_actor_task(self, spec: TaskSpec,
                                  want_ph: int = 1) -> dict:
        sem = self._actor_semaphore
        if spec.concurrency_group:
            sem = getattr(self, "_group_semaphores", {}).get(
                spec.concurrency_group, sem)
        ph = self._new_exec_phases(want_ph)
        async with sem:
            self.current_task_id = spec.task_id
            span = None
            try:
                method = getattr(self.executing_actor, spec.method_name)
                args, kwargs = await self._resolve_task_args(spec)
                if ph is not None:
                    ph[PH_ARGS_READY] = time.time()
                # Span covers user code only (same as normal tasks).
                span = self._maybe_start_span(spec)
                if spec.is_generator:
                    return await self._execute_generator_task(
                        spec, method, args, kwargs)
                if ph is not None:
                    ph[PH_EXEC_START] = time.time()
                if asyncio.iscoroutinefunction(method):
                    task = asyncio.ensure_future(method(*args, **kwargs))
                    self._running_tasks[spec.task_id] = task
                    result = await task
                else:
                    fut = self._run_in_pool(method, *args, **kwargs)
                    self._running_tasks[spec.task_id] = fut
                    result = await fut
                if ph is not None:
                    ph[PH_EXEC_END] = time.time()
                values = self._split_returns(result, spec.num_returns)
                returns = await self._store_returns(spec, values)
                if ph is not None:
                    ph[PH_RESULT_PUT] = time.time()
                return (returns, ph)
            except _DependencyError as e:
                return self._app_error_envelope(e.error, None)
            except asyncio.CancelledError:
                return {"cancelled": True}
            except Exception as e:  # noqa: BLE001
                import os as _os
                err = exc.TaskError(e, traceback.format_exc(), spec.task_id,
                                    _os.getpid())
                returns = await self._store_returns(
                    spec, [err] * spec.num_returns, is_exception=True)
                envelope = self._app_error_envelope(err, returns)
                if ph is not None:
                    envelope["phases"] = ph
                return envelope
            finally:
                self._finish_span(span)
                self._running_tasks.pop(spec.task_id, None)
                self.current_task_id = None

    @rpc.idempotent
    async def _rpc_kill_actor(self, conn, payload):
        if self.executing_actor is not None:
            inst = self.executing_actor
            if hasattr(inst, "__ray_terminate__"):
                try:
                    inst.__ray_terminate__()
                except Exception:
                    pass
        self._shutdown = True
        self.loop.call_soon(self.loop.stop)
        return True

    # ==================================================================
    # task events
    # ==================================================================

    _TASK_STATE_COUNTERS: Dict[str, Any] = {}
    # Hot-path histogram slots for per-phase latencies: one registry slot
    # per PHASE_ORDER index (+"total" at the end), resolved once per
    # process (same caching pattern as the state counters). The caches
    # remember the registry generation they were built at: a
    # metrics.clear() discards the registry, and writing into orphaned
    # slot dicts would silently drop every later sample.
    _PHASE_HIST_SLOTS: Optional[list] = None
    _BATCH_HIST_SLOTS: Dict[str, Any] = {}
    _SLOT_CACHE_GEN: int = -1
    # Buckets sized for a control plane whose phases span ~100us..10s.
    _PHASE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                      0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                      10.0)

    @classmethod
    def _check_slot_caches(cls, generation: int):
        """Drop slot caches built against a cleared registry."""
        if cls._SLOT_CACHE_GEN != generation:
            cls._SLOT_CACHE_GEN = generation
            cls._PHASE_HIST_SLOTS = None
            cls._BATCH_HIST_SLOTS = {}
            cls._TASK_STATE_COUNTERS = {}

    def _stamp_phase(self, task_id: TaskID, idx: int,
                     t: Optional[float] = None):
        """Owner-side flight-recorder stamp (no-op with events off)."""
        if not self.config.task_events_enabled:
            return
        pt = self.pending_tasks.get(task_id)
        if pt is None:
            return
        ph = pt.phases
        if ph is None:
            ph = pt.phases = [None] * RECORD_LEN
        ph[idx] = time.time() if t is None else t

    @classmethod
    def _build_phase_slots(cls) -> list:
        from ray_tpu.util import metrics as _metrics
        hist = _metrics.Histogram(
            "ray_tpu_task_phase_seconds",
            "task lifecycle phase latency (flight recorder)",
            boundaries=cls._PHASE_BUCKETS, tag_keys=("Phase",))
        slots = [hist._slot({"Phase": name}) for name in PHASE_ORDER]
        slots.append(hist._slot({"Phase": "total"}))
        cls._PHASE_HIST_SLOTS = slots
        return slots

    def _observe_phases(self, ph: list):
        """Fold one finished task's stamps into the per-phase histograms.

        Hot path (runs per task reply): fixed-index walk, ONE lock round,
        direct slot updates — no intermediate structures."""
        from ray_tpu.util import metrics as _metrics
        self._check_slot_caches(_metrics._generation)
        slots = self._PHASE_HIST_SLOTS or self._build_phase_slots()
        with _metrics._lock:
            prev = None
            for i in range(N_STAMPS):
                t = ph[i]
                if t is None:
                    continue
                if prev is not None:
                    _metrics.observe_locked(slots[i], max(0.0, t - prev))
                prev = t
            t0, t1 = ph[PH_SUBMITTED], ph[PH_REPLY_HANDLED]
            if t0 is not None and t1 is not None:
                _metrics.observe_locked(slots[N_STAMPS],
                                        max(0.0, t1 - t0))

    def _observe_batch_size(self, kind: str, n: int):
        """Dispatch batch-size distribution (the self-clocking pipeline's
        health signal: 1 = singles, larger = coalescing works)."""
        if not self.config.task_events_enabled:
            return
        from ray_tpu.util import metrics as _m
        self._check_slot_caches(_m._generation)
        ent = self._BATCH_HIST_SLOTS.get(kind)
        if ent is None:
            from ray_tpu.util import metrics as _metrics
            hist = _metrics.Histogram(
                "ray_tpu_dispatch_batch_size",
                "specs per push RPC (task and actor dispatch pipelines)",
                boundaries=(1, 2, 4, 8, 16, 32, 64, 128, 256),
                tag_keys=("Kind",))
            ent = hist._slot({"Kind": kind})
            self._BATCH_HIST_SLOTS[kind] = ent
        from ray_tpu.util import metrics as _metrics
        _metrics.observe_into(ent, float(n))

    def _finish_phase_record(
            self, pt: Optional[PendingTask]) -> Optional[list]:
        """Stamp reply_handled, feed the histograms, and return the
        merged phase record to ride the terminal task event."""
        if pt is None or pt.phases is None \
                or not self.config.task_events_enabled:
            return None
        ph = pt.phases
        ph[PH_REPLY_HANDLED] = time.time()
        self._observe_phases(ph)
        return ph

    def _record_task_event(self, spec: TaskSpec, state: str,
                           phases: Optional[list] = None,
                           t: Optional[float] = None):
        if not self.config.task_events_enabled:
            return
        from ray_tpu.util import metrics as _m
        self._check_slot_caches(_m._generation)
        ent = self._TASK_STATE_COUNTERS.get(state)
        if ent is None:
            # Resolve the registry slot once per state: Metric.inc()'s
            # tag-merge + key-sort per call is measurable on the submission
            # hot path (~20us each, 3 events per task).
            from ray_tpu.util import metrics as _metrics
            from ray_tpu.util.metrics import Counter as _Counter
            counter = _Counter("ray_tpu_tasks_total",
                               "task state transitions", tag_keys=("State",)
                               ).set_default_tags({"State": state})
            counter.inc(0)
            k = _metrics._key("ray_tpu_tasks_total", {"State": state})
            ent = (_metrics._lock, _metrics._registry[k])
            self._TASK_STATE_COUNTERS[state] = ent
        lock, slot = ent
        with lock:
            slot["value"] += 1
        # Hex/dict formatting deferred to flush time (off the hot path).
        # Fixed-slot ring write: no per-event tuple, no list growth, and
        # overflow (GCS unreachable for a long stretch) is O(1)
        # drop-oldest instead of a list slice. Fields only — holding the
        # spec would pin its inline arg payloads past task completion.
        pending = self._task_events.record(
            spec.task_id.binary(), spec.job_id.binary(),
            spec.name or spec.method_name or spec.function_id, state,
            time.time() if t is None else t,
            spec.actor_id.binary() if spec.actor_id else None,
            spec.resources, phases)
        if pending > 1000 and not self._te_flush_scheduled:
            self._te_flush_scheduled = True
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                # Threadsafe submission path: flush from the loop.
                self.loop.call_soon_threadsafe(self._spawn_event_flush)
            else:
                self._spawn_event_flush()

    def _spawn_event_flush(self):
        asyncio.ensure_future(self._flush_task_events())

    def _task_event_dict(self, task_id: bytes, job_id: bytes, name: str,
                         state: str, t: float, actor_id, resources,
                         phases=None) -> dict:
        out = {
            "task_id": task_id.hex(), "job_id": job_id.hex(),
            "name": name, "state": state, "time": t,
            "actor_id": actor_id.hex() if actor_id else None,
            "resources": resources,
            "worker_id": self._worker_hex,
        }
        if phases:
            out["phases"] = phases
        return out

    async def _flush_task_events(self):
        self._te_flush_scheduled = False
        if self.gcs is None or self.gcs.closed:
            return  # ring keeps the window; overflow drops oldest in O(1)
        if not len(self._task_events) and not self._span_events:
            return
        buf = self._task_events.drain()
        spans, self._span_events = self._span_events, []
        # Coalesce within the flush window: a task that reached a terminal
        # state here ships ONLY its terminal event when that event carries
        # the full phase record — its PENDING/RUNNING rows are superseded
        # (the latest-state queries reduce them away anyway, and the
        # timeline draws the slice from the phases). For a fast-task
        # burst this cuts the wire+GCS load to a third. Tasks still in
        # flight keep their intermediate rows.
        done_with_phases = {
            e[0] for e in buf
            if e[7] is not None and e[3] in ("FINISHED", "FAILED")}
        events = [self._task_event_dict(*e)
                  for e in buf
                  if e[3] in ("FINISHED", "FAILED")
                  or e[0] not in done_with_phases]
        if spans:
            events.extend(spans)
        if not events:
            return
        try:
            await self.gcs.request("report_task_events", {"events": events})
        except rpc.RpcError:
            pass

    async def _flush_task_events_loop(self):
        while not self._shutdown:
            await asyncio.sleep(1.0)
            try:
                await self._flush_task_events()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one bad flush must not
                logger.exception("task-event flush failed")  # kill the loop


class _DependencyError(Exception):
    def __init__(self, error):
        self.error = error
        super().__init__(str(error))
