"""Serialization: cloudpickle + pickle-5 out-of-band zero-copy buffers.

Capability parity with python/ray/_private/serialization.py: functions/classes
go through cloudpickle; numpy (and jax-on-host) arrays are serialized
out-of-band so large tensors are written into / read from the shared-memory
object store with zero copies; ObjectRefs contained in values are collected on
serialize and re-registered (borrowed) on deserialize.

Wire layout (8-byte aligned so numpy views map directly onto shm):
    u32 magic | u32 n_buffers | u64 sizes[n] | pad to 8 | buf0 (inband pickle)
    | pad | buf1 | pad | ...
"""

from __future__ import annotations

import pickle
import struct
import threading
from typing import Any, Callable, List, Optional

import cloudpickle

from ray_tpu._private.object_ref import ObjectRef

MAGIC = 0x52545055  # "RTPU"
_ALIGN = 8


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class SerializedObject:
    __slots__ = ("buffers", "contained_refs")

    def __init__(self, buffers: List[memoryview], contained_refs: List[ObjectRef]):
        self.buffers = buffers
        self.contained_refs = contained_refs

    @property
    def total_size(self) -> int:
        header = 8 + 8 * len(self.buffers)
        size = _pad(header)
        for b in self.buffers:
            size = _pad(size + b.nbytes)
        return size

    def write_to(self, dest: memoryview) -> int:
        n = len(self.buffers)
        struct.pack_into("<II", dest, 0, MAGIC, n)
        off = 8
        for b in self.buffers:
            struct.pack_into("<Q", dest, off, b.nbytes)
            off += 8
        off = _pad(off)
        for b in self.buffers:
            dest[off : off + b.nbytes] = b.cast("B") if b.format != "B" else b
            off = _pad(off + b.nbytes)
        return off

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size)
        self.write_to(memoryview(out))
        return bytes(out)


# Thread-local context used to thread contained-ref collection through pickle.
_ctx = threading.local()


def _objectref_reducer(ref: ObjectRef):
    lst = getattr(_ctx, "refs", None)
    if lst is not None:
        lst.append(ref)
    return (_restore_ref, (ref.id, ref.owner_address))


def _restore_ref(object_id, owner_address):
    cb = getattr(_ctx, "deser_ref_cb", None)
    if cb is not None:
        return cb(object_id, owner_address)
    return ObjectRef(object_id, owner_address, skip_refcount=True)


class _Pickler(cloudpickle.CloudPickler):
    dispatch_table = dict(getattr(cloudpickle.CloudPickler, "dispatch_table", {}))

    def reducer_override(self, obj):
        if isinstance(obj, ObjectRef):
            return _objectref_reducer(obj)
        return super().reducer_override(obj)


class SerializationContext:
    """Per-worker serializer with custom-serializer registry."""

    def __init__(self):
        self._custom: dict[type, tuple[Callable, Callable]] = {}
        # Called with (ObjectID, owner_address) on deserialization of a
        # contained ref; installed by the core worker to register borrowers.
        self.deserialized_ref_factory: Optional[Callable] = None

    def register_custom_serializer(self, cls: type, serializer: Callable,
                                   deserializer: Callable):
        self._custom[cls] = (serializer, deserializer)
        cp = self._custom

        def _reduce(obj):
            ser, deser = cp[type(obj)]
            return (deser, (ser(obj),))

        _Pickler.dispatch_table[cls] = _reduce

    def serialize(self, value: Any) -> SerializedObject:
        import io

        _ctx.refs = []
        buffers: List[pickle.PickleBuffer] = []
        try:
            f = io.BytesIO()
            p = _Pickler(f, protocol=5, buffer_callback=buffers.append)
            p.dump(value)
            inband = f.getvalue()
            refs = list(_ctx.refs)
        finally:
            _ctx.refs = None
        views = [memoryview(inband)]
        for pb in buffers:
            views.append(pb.raw())
        return SerializedObject(views, refs)

    def deserialize(self, data) -> Any:
        if isinstance(data, (bytes, bytearray)):
            data = memoryview(data)
        magic, n = struct.unpack_from("<II", data, 0)
        if magic != MAGIC:
            raise ValueError("corrupt serialized object (bad magic)")
        sizes = struct.unpack_from(f"<{n}Q", data, 8)
        off = _pad(8 + 8 * n)
        bufs = []
        for s in sizes:
            bufs.append(data[off : off + s])
            off = _pad(off + s)
        _ctx.deser_ref_cb = self.deserialized_ref_factory
        try:
            return pickle.loads(bufs[0], buffers=bufs[1:])
        finally:
            _ctx.deser_ref_cb = None

    # -- convenience one-shot helpers (control-plane metadata, small values) --
    def dumps(self, value: Any) -> bytes:
        return self.serialize(value).to_bytes()

    def loads(self, data) -> Any:
        return self.deserialize(data)


_default_context: Optional[SerializationContext] = None


def get_serialization_context() -> SerializationContext:
    global _default_context
    if _default_context is None:
        _default_context = SerializationContext()
    return _default_context
