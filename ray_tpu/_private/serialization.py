"""Serialization: cloudpickle + pickle-5 out-of-band zero-copy buffers.

Capability parity with python/ray/_private/serialization.py: functions/classes
go through cloudpickle; numpy (and jax-on-host) arrays are serialized
out-of-band so large tensors are written into / read from the shared-memory
object store with zero copies; ObjectRefs contained in values are collected on
serialize and re-registered (borrowed) on deserialize.

Wire layout (8-byte aligned so numpy views map directly onto shm):
    u32 magic | u32 n_buffers | u64 sizes[n] | pad to 8 | buf0 (inband pickle)
    | pad | buf1 | pad | ...
"""

from __future__ import annotations

import os
import pickle
import struct
import sys
import sysconfig
import threading
from typing import Any, Callable, List, Optional

import cloudpickle

from ray_tpu._private.object_ref import ObjectRef

# ---------------------------------------------------------------------------
# Driver-module pickle-by-value registration.
#
# cloudpickle serializes functions/classes defined in *importable* modules by
# reference (module name + qualname). That is correct for installed libraries
# but wrong for driver-local modules (a user script, a pytest test module):
# workers on other nodes do not have the driver's sys.path, so unpickling
# dies with ModuleNotFoundError. The reference ships code through the GCS
# function table with by-value pickling of the function AND the driver-module
# globals it references (python/ray/_private/function_manager.py). We get the
# same effect by registering any non-stdlib/site-packages module with
# cloudpickle.register_pickle_by_value before pickling user functions — the
# whole closure (referenced module globals included) then travels by value.
# ---------------------------------------------------------------------------

_LIB_PATHS = tuple(
    os.path.abspath(p) + os.sep
    for p in {
        sysconfig.get_paths().get("stdlib"),
        sysconfig.get_paths().get("platstdlib"),
        sysconfig.get_paths().get("purelib"),
        sysconfig.get_paths().get("platlib"),
    }
    if p
)
_by_value_registered: set = set()


def _is_driver_local_module(mod) -> bool:
    """True for modules that exist only on the driver's sys.path.

    Known limitation: an editable install (`pip install -e`) lives outside
    site-packages and is treated as driver-local, so it ships by value even
    though workers could import it — wasteful but correct for same-code
    clusters. The reference has the inverse problem (by-reference pickling
    of genuinely driver-local modules), which is the worse failure mode.
    """
    if mod is None:
        return False
    name = getattr(mod, "__name__", "")
    if not name or name in ("__main__", "__mp_main__"):
        return False  # cloudpickle already pickles __main__ by value
    if name.split(".")[0] == "ray_tpu":
        return False  # the framework itself is importable on every worker
    path = getattr(mod, "__file__", None)
    if path is None:
        return False  # builtin / C extension
    path = os.path.abspath(path)
    if "site-packages" in path or "dist-packages" in path:
        return False
    return not any(path.startswith(p) for p in _LIB_PATHS)


def _register_module_tree(mod) -> None:
    """Register a driver-local module and, recursively, every driver-local
    module reachable through its globals (``import helpers`` in a test
    module must also travel by value, or functions it defines would still
    pickle by reference and fail on remote nodes)."""
    name = getattr(mod, "__name__", None)
    if not name or name in _by_value_registered:
        return
    _by_value_registered.add(name)
    if not _is_driver_local_module(mod):
        return
    try:
        cloudpickle.register_pickle_by_value(mod)
    except Exception:
        return
    import types
    for attr in list(vars(mod).values()):
        if isinstance(attr, types.ModuleType):
            _register_module_tree(attr)
        else:
            sub_name = getattr(attr, "__module__", None)
            if sub_name and sub_name not in _by_value_registered:
                sub = sys.modules.get(sub_name)
                if sub is not None:
                    _register_module_tree(sub)


def ensure_pickle_by_value(obj) -> None:
    """Register obj's defining module (if driver-local) for by-value pickling."""
    mod_name = getattr(obj, "__module__", None)
    if not mod_name or mod_name in _by_value_registered:
        return
    mod = sys.modules.get(mod_name)
    if mod is not None:
        _register_module_tree(mod)
    else:
        _by_value_registered.add(mod_name)


def dumps_function(obj) -> bytes:
    """cloudpickle.dumps for user functions/classes, shipping driver-local
    modules by value so remote nodes can always deserialize them."""
    ensure_pickle_by_value(obj)
    return cloudpickle.dumps(obj)

MAGIC = 0x52545055  # "RTPU"
_ALIGN = 8


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _parallel_copy(dst, src, nthreads: int = 2):
    """Split one huge memcpy across threads (numpy copies release the GIL,
    so multiple cores can drive the memory channels in parallel). Strictly
    a loss on few-core boxes — context switches serialize the halves — so
    callers must gate on cpu_count; only worth it for very large buffers
    (thread start/join costs ~100us)."""
    nthreads = min(nthreads, os.cpu_count() or 1)
    if nthreads < 2:
        dst[:] = src
        return
    n = len(src)
    step = (n + nthreads - 1) // nthreads
    step = (step + 4095) // 4096 * 4096  # page-align the split
    workers = []
    for start in range(step, n, step):
        end = min(start + step, n)
        t = threading.Thread(
            target=lambda s=start, e=end: dst[s:e].__setitem__(
                slice(None), src[s:e]))
        t.start()
        workers.append(t)
    dst[:min(step, n)] = src[:min(step, n)]
    for t in workers:
        t.join()


class SerializedObject:
    __slots__ = ("buffers", "contained_refs", "credited_ids")

    def __init__(self, buffers: List[memoryview],
                 contained_refs: List[ObjectRef],
                 credited_ids: Optional[list] = None):
        self.buffers = buffers
        self.contained_refs = contained_refs
        # ObjectIDs that received a handoff credit during THIS
        # serialization (self-owned refs leaving the process). A
        # container stored locally records these so freeing the
        # never-deserialized container returns the credits.
        self.credited_ids = credited_ids or []

    @property
    def total_size(self) -> int:
        header = 8 + 8 * len(self.buffers)
        size = _pad(header)
        for b in self.buffers:
            size = _pad(size + b.nbytes)
        return size

    def write_to(self, dest: memoryview) -> int:
        n = len(self.buffers)
        struct.pack_into("<II", dest, 0, MAGIC, n)
        off = 8
        for b in self.buffers:
            struct.pack_into("<Q", dest, off, b.nbytes)
            off += 8
        off = _pad(off)
        for b in self.buffers:
            bb = b.cast("B") if b.format != "B" else b
            if bb.nbytes > (1 << 20):
                # numpy's memcpy path moves bytes ~1.5x faster than
                # memoryview slice-assignment of a format-cast view
                # (measured 7.9 vs 5.1 GB/s warm on this box).
                import numpy as _np
                src = _np.frombuffer(bb, _np.uint8)
                dst = _np.frombuffer(dest[off:off + bb.nbytes], _np.uint8)
                if bb.nbytes >= (64 << 20) and (os.cpu_count() or 1) >= 4:
                    _parallel_copy(dst, src)
                else:
                    dst[:] = src
            else:
                dest[off : off + bb.nbytes] = bb
            off = _pad(off + b.nbytes)
        return off

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size)
        self.write_to(memoryview(out))
        return bytes(out)


# Thread-local context used to thread contained-ref collection through pickle.
_ctx = threading.local()

# Set by the core worker: called for every serialized contained ref.
# Returns True when a HANDOFF CREDIT was granted — the serializing
# process owns the object and pre-registered one borrow on it, so the
# object cannot be freed while the serialized value (and the async
# borrow registration of whoever deserializes it) is in flight. Without
# the credit there is a window where the owner's refcount hits zero
# after the value left the process but before the receiver's
# owner_add_borrower notify lands (premature free, shaken out by RPC
# delay injection on the data suite).
_handoff_credit_cb = None
# Inverse of the grant callback: called with a list of ObjectIDs whose
# granted credits must be RETURNED because the serialization that granted
# them failed partway (the bytes never exist, so no receiver will ever
# consume the credits).
_handoff_return_cb = None


def _set_handoff_credit_cb(cb, return_cb=None):
    global _handoff_credit_cb, _handoff_return_cb
    _handoff_credit_cb = cb
    _handoff_return_cb = return_cb


def _objectref_reducer(ref: ObjectRef):
    lst = getattr(_ctx, "refs", None)
    if lst is not None:
        lst.append(ref)
    credited = False
    cb = _handoff_credit_cb
    if cb is not None:
        try:
            credited = bool(cb(ref))
        except Exception:
            credited = False
    if credited:
        cl = getattr(_ctx, "credited", None)
        if cl is not None:
            cl.append(ref.id)
    return (_restore_ref, (ref.id, ref.owner_address, credited))


def _restore_ref(object_id, owner_address, credited: bool = False):
    cb = getattr(_ctx, "deser_ref_cb", None)
    if cb is not None:
        return cb(object_id, owner_address, credited)
    return ObjectRef(object_id, owner_address, skip_refcount=True)


class _Pickler(cloudpickle.CloudPickler):
    dispatch_table = dict(getattr(cloudpickle.CloudPickler, "dispatch_table", {}))

    def reducer_override(self, obj):
        if isinstance(obj, ObjectRef):
            return _objectref_reducer(obj)
        return super().reducer_override(obj)


class SerializationContext:
    """Per-worker serializer with custom-serializer registry."""

    def __init__(self):
        self._custom: dict[type, tuple[Callable, Callable]] = {}
        # Called with (ObjectID, owner_address) on deserialization of a
        # contained ref; installed by the core worker to register borrowers.
        self.deserialized_ref_factory: Optional[Callable] = None

    def register_custom_serializer(self, cls: type, serializer: Callable,
                                   deserializer: Callable):
        self._custom[cls] = (serializer, deserializer)
        cp = self._custom

        def _reduce(obj):
            ser, deser = cp[type(obj)]
            return (deser, (ser(obj),))

        _Pickler.dispatch_table[cls] = _reduce

    # Scalar types that can neither contain ObjectRefs nor produce
    # out-of-band buffers: plain C pickle handles them whole, skipping the
    # CloudPickler construction (~4us -> ~0.5us per serialize; arg/return
    # values on the actor-call hot path are mostly these).
    _FAST_SCALARS = frozenset((type(None), bool, int, float, str, bytes))

    def serialize_inline(self, value: Any,
                         limit: Optional[int] = None) -> Optional[bytes]:
        """One-pass wire bytes for fast scalars, or None when the value
        needs the general path (container, custom serializer, or bigger
        than `limit`). Equivalent bytes to serialize().to_bytes() but
        with a single allocation instead of SerializedObject + memoryview
        + bytearray + copy — the dominant per-argument cost on the
        hot submit path."""
        t = type(value)
        if t not in self._FAST_SCALARS or t in self._custom:
            return None
        body = pickle.dumps(value, protocol=5)
        n = len(body)
        if limit is not None and n + 16 > limit:
            return None
        # Layout: u32 magic | u32 n=1 | u64 size | buf0 | pad-to-8 —
        # header is 16 bytes (already 8-aligned with one buffer).
        return b"".join((struct.pack("<IIQ", MAGIC, 1, n), body,
                         b"\x00" * (-(16 + n) % 8)))

    def serialize(self, value: Any) -> SerializedObject:
        if type(value) in self._FAST_SCALARS and type(value) not in self._custom:
            return SerializedObject(
                [memoryview(pickle.dumps(value, protocol=5))], [], [])
        import io

        _ctx.refs = []
        _ctx.credited = []
        buffers: List[pickle.PickleBuffer] = []
        try:
            f = io.BytesIO()
            p = _Pickler(f, protocol=5, buffer_callback=buffers.append)
            p.dump(value)
            inband = f.getvalue()
            refs = list(_ctx.refs)
            credited = list(_ctx.credited)
        except Exception:
            # A later field failed to pickle AFTER contained refs already
            # granted handoff credits: those bytes will never exist, so
            # return the in-flight grants here (the caller only sees the
            # exception, never the partial credited list).
            inflight = list(_ctx.credited or [])
            if inflight and _handoff_return_cb is not None:
                try:
                    _handoff_return_cb(inflight)
                except Exception:
                    pass
            raise
        finally:
            _ctx.refs = None
            _ctx.credited = None
        views = [memoryview(inband)]
        for pb in buffers:
            views.append(pb.raw())
        return SerializedObject(views, refs, credited)

    def deserialize(self, data) -> Any:
        if isinstance(data, (bytes, bytearray)):
            data = memoryview(data)
        magic, n = struct.unpack_from("<II", data, 0)
        if magic != MAGIC:
            raise ValueError("corrupt serialized object (bad magic)")
        sizes = struct.unpack_from(f"<{n}Q", data, 8)
        off = _pad(8 + 8 * n)
        bufs = []
        for s in sizes:
            bufs.append(data[off : off + s])
            off = _pad(off + s)
        _ctx.deser_ref_cb = self.deserialized_ref_factory
        try:
            return pickle.loads(bufs[0], buffers=bufs[1:])
        finally:
            _ctx.deser_ref_cb = None

    # -- convenience one-shot helpers (control-plane metadata, small values) --
    def dumps(self, value: Any) -> bytes:
        return self.serialize(value).to_bytes()

    def loads(self, data) -> Any:
        return self.deserialize(data)


_default_context: Optional[SerializationContext] = None


def get_serialization_context() -> SerializationContext:
    global _default_context
    if _default_context is None:
        _default_context = SerializationContext()
    return _default_context


def context_for_process() -> SerializationContext:
    """The live core worker's context when one exists, else the module
    default. Out-of-task serializers (shm channels) must prefer the
    core's context so contained ObjectRefs get the same handoff-credit /
    borrower registration as the task path — the bare default context
    would round-trip refs without refcounting."""
    try:
        from ray_tpu._private import worker_api
        core = worker_api.peek_core()
        if core is not None:
            return core.serialization
    except Exception:  # noqa: BLE001 — import cycle during teardown
        pass
    return get_serialization_context()
