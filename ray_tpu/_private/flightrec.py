"""Flight recorder: phase-stamp vocabulary + Chrome-trace assembly.

The task lifecycle is stamped at every hop (owner submit -> lease wait ->
lease grant -> dispatch -> worker receive -> args ready -> exec ->
result put -> owner reply handling). Owners keep their stamps on the
PendingTask; executors ship theirs back inside the task reply; the merged
record rides the FINISHED/FAILED task event to the GCS, where every
observability surface (timeline, /api/latency, summarize_tasks latency
columns, per-phase Prometheus histograms) reads the same record.

Wire/memory format: a phase record is a fixed-size LIST indexed by the
PH_* constants below (stamps are wall-clock floats, missing = None; the
last slot carries the executing worker's id). A positional list of
floats costs a fraction of a string-keyed dict to stamp, pickle, and
fold — the recorder rides the task hot path, so the dict form exists
only at the query surfaces (as_dict).

Stamps are wall-clock (`time.time()`): every daemon of this framework
shares a host (127.0.0.1 control plane), so cross-process gaps are
directly comparable; within-process durations are exact.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Ordered stamp names. A phase duration is the gap between two consecutive
# *present* stamps, reported under the LATER stamp's name (e.g. the
# "exec_end" phase is the user-code execution time; "received" is the
# dispatch->worker wire+decode gap). Not every task carries every stamp:
# actor calls skip the lease stamps, failed tasks stop wherever they died.
PHASE_ORDER = (
    "submitted",       # owner: .remote() accepted the call
    "lease_wait",      # owner: spec entered the per-class dispatch queue
    "lease_granted",   # owner: spec assigned to a leased worker slot
    "dispatched",      # owner: push RPC handed to the transport
    "received",        # worker: push handler started processing the spec
    "args_ready",      # worker: argument resolution finished
    "exec_start",      # worker: user code entered
    "exec_end",        # worker: user code returned
    "result_put",      # worker: returns serialized/stored
    "reply_handled",   # owner: reply applied, return objects ready
)

# Record-slot indices (a record is [*stamps, worker_hex]).
(PH_SUBMITTED, PH_LEASE_WAIT, PH_LEASE_GRANTED, PH_DISPATCHED,
 PH_RECEIVED, PH_ARGS_READY, PH_EXEC_START, PH_EXEC_END,
 PH_RESULT_PUT, PH_REPLY_HANDLED) = range(10)
N_STAMPS = 10
IDX_WORKER = 10
RECORD_LEN = 11


def new_record() -> list:
    return [None] * RECORD_LEN


# Fields of one owner-side task-event record (see EventRing).
EVENT_FIELDS = 8


class EventRing:
    """Fixed-slot ring buffer for owner-side task events.

    The recorder rides the submit/reply hot path: one event per state
    transition, three per task. The previous list-of-tuples buffer paid
    a tuple allocation per event plus list growth and a slicing trim on
    overflow; the ring pre-allocates `capacity` reusable 8-slot records
    and a write is eight slot stores under one small uncontended lock.
    Events fold into wire dicts only at flush (`drain`), off the hot
    path.

    Overflow is drop-oldest: a writer that laps the flush cursor
    overwrites unflushed records (the old buffer's del-oldest-10k
    behavior, now O(1)); `dropped` counts the loss.

    Slot writes AND the drain copy both run under the lock: index
    reservation alone would let a drain racing a mid-write slot ship a
    torn (or all-None) record. Drain holds the lock for its whole copy
    — bounded by capacity, ~100us for a 1000-event flush window, paid
    once per flush, not per event.
    """

    __slots__ = ("_slots", "_mask", "_head", "_tail", "_lock", "dropped")

    def __init__(self, capacity: int = 16384):
        cap = 1 << (capacity - 1).bit_length()
        self._slots = [[None] * EVENT_FIELDS for _ in range(cap)]
        self._mask = cap - 1
        self._head = 0
        self._tail = 0
        self._lock = threading.Lock()
        self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return min(self._head - self._tail, self._mask + 1)

    def record(self, f0, f1, f2, f3, f4, f5, f6, f7) -> int:
        """Write one event; returns the approximate pending count."""
        with self._lock:
            i = self._head
            self._head = i + 1
            slot = self._slots[i & self._mask]
            slot[0] = f0
            slot[1] = f1
            slot[2] = f2
            slot[3] = f3
            slot[4] = f4
            slot[5] = f5
            slot[6] = f6
            slot[7] = f7
            return i + 1 - self._tail

    def drain(self) -> list:
        """Copy out pending records oldest-first as tuples and advance the
        flush cursor. Overwritten (lapped) records are skipped and counted
        in `dropped`."""
        with self._lock:
            head = self._head
            i = self._tail
            cap = self._mask + 1
            if head - i > cap:
                self.dropped += head - i - cap
                i = head - cap
            out = []
            slots = self._slots
            mask = self._mask
            while i < head:
                s = slots[i & mask]
                out.append((s[0], s[1], s[2], s[3], s[4], s[5], s[6],
                            s[7]))
                i += 1
            self._tail = head
            return out


def as_dict(rec: Optional[Sequence]) -> Dict[str, Any]:
    """Named view of a phase record (query surfaces / debugging only)."""
    if not rec:
        return {}
    out = {PHASE_ORDER[i]: rec[i]
           for i in range(N_STAMPS) if rec[i] is not None}
    if len(rec) > IDX_WORKER and rec[IDX_WORKER]:
        out["w"] = rec[IDX_WORKER]
    return out


def phase_durations(rec: Sequence) -> List[Tuple[str, float]]:
    """(phase, seconds) for every consecutive pair of present stamps,
    plus ("total", submit->reply) when both endpoints exist. Negative
    gaps (cross-process clock skew) clamp to zero."""
    out: List[Tuple[str, float]] = []
    prev: Optional[float] = None
    for i in range(N_STAMPS):
        t = rec[i]
        if t is None:
            continue
        if prev is not None:
            out.append((PHASE_ORDER[i], max(0.0, t - prev)))
        prev = t
    t0, t1 = rec[PH_SUBMITTED], rec[PH_REPLY_HANDLED]
    if t0 is not None and t1 is not None:
        out.append(("total", max(0.0, t1 - t0)))
    return out


# ---------------------------------------------------------------------------
# Serve request phases (the request-scoped twin of the task vocabulary).
#
# A serve request crosses three hops — proxy ingress, handle/router, and
# replica — each of which stamps the subset of phases it owns into one
# fixed-index record and ships it as a `kind:"serve_request"` event on
# the same task-event channel (serve/request_trace.py owns the ring +
# flush). A phase duration is the gap between two consecutive present
# stamps, reported under the LATER stamp's name, exactly like tasks.
# ---------------------------------------------------------------------------

REQ_PHASE_ORDER = (
    "proxy_recv",      # proxy: request fully parsed off the socket
    "admission",       # replica: request arrived at the admission gate
    "queue_wait",      # replica: execution slot acquired (gap = queueing)
    "dispatch",        # proxy/handle: payload handed to handle.remote()
    "exec_start",      # replica: handler entered
    "exec_end",        # replica: handler returned
    "first_item",      # replica: first streamed item yielded
    "reply",           # hop-local: reply delivered / stream finished
    # Continuous-batching phase split (serve/continuous_batching.py):
    # the gap exec_start -> prefill_end is the sequence's prefill time,
    # prefill_end -> exec_end its decode time. Appended AFTER the
    # original eight so existing fixed-index records stay valid;
    # request_phase_durations sorts stamps by time, so position in this
    # tuple never inverts a gap.
    "prefill_end",     # replica: sequence left the prefill phase
)
(RQ_PROXY_RECV, RQ_ADMISSION, RQ_QUEUE_WAIT, RQ_DISPATCH, RQ_EXEC_START,
 RQ_EXEC_END, RQ_FIRST_ITEM, RQ_REPLY, RQ_PREFILL_END) = range(9)
REQ_RECORD_LEN = 9


def new_request_record() -> list:
    return [None] * REQ_RECORD_LEN


def request_phase_durations(rec: Sequence) -> List[Tuple[str, float]]:
    """(phase, seconds) pairs for one hop's request record, plus a
    ("total", first->last) row. Stamp order follows REQ_PHASE_ORDER
    except `dispatch`, which the proxy stamps BEFORE the replica's
    phases happen — sort present stamps by time so cross-hop records
    never produce inverted gaps."""
    # min(): records written by a pre-prefill_end process are 8 slots —
    # a version-skewed reader must fold them, not IndexError.
    present = [(rec[i], REQ_PHASE_ORDER[i])
               for i in range(min(len(rec), REQ_RECORD_LEN))
               if rec[i] is not None]
    present.sort()
    out: List[Tuple[str, float]] = []
    for (t0, _n0), (t1, n1) in zip(present, present[1:]):
        out.append((n1, max(0.0, t1 - t0)))
    if len(present) >= 2:
        out.append(("total", max(0.0, present[-1][0] - present[0][0])))
    return out


def span_event(name: str, trace_id: str, start: float, end: float,
               **extra) -> dict:
    """One kind:"span" task-event record — the wire shape get_spans()
    and the timeline consume — for spans recorded OUTSIDE util/tracing's
    contextvar machinery: the GCS gang-drain spans and the compiled-DAG
    dag:compile / dag:tick spans build these directly (a contextvar span
    would mis-parent them under whatever task happens to be running)."""
    import os as _os
    return {"kind": "span", "trace_id": trace_id,
            "span_id": _os.urandom(8).hex(), "parent_id": "",
            "name": name, "task_id": trace_id, "start": start, "end": end,
            "pid": _os.getpid(), **extra}


# Worker-lane sub-slices drawn inside the task slice on the timeline.
SUB_SLICES = (
    ("args_resolve", PH_RECEIVED, PH_ARGS_READY),
    ("exec", PH_EXEC_START, PH_EXEC_END),
    ("result_put", PH_EXEC_END, PH_RESULT_PUT),
)

_EMPTY: tuple = (None,) * RECORD_LEN


def build_trace(events: List[dict]) -> List[dict]:
    """Chrome-trace (chrome://tracing / Perfetto) event list from raw task
    events.

    Emits, per completed task:
      - the task slice ("X", cat "task") on the executing worker's lane;
      - phase sub-slices ("X", cat "phase", tid 1) nested inside it
        (args_resolve / exec / result_put), clamped into the task slice;
      - a "submit" slice on the owner's lane covering submit->dispatch;
      - one flow-event pair (ph "s"/"f", shared id) connecting the submit
        on the owner to the execution start on the worker across pids.
    Span records (tracing.enable()) are skipped — get_spans() owns those.
    """
    trace: List[dict] = []
    starts: Dict[str, dict] = {}
    serve_events = [e for e in events if isinstance(e, dict)
                    and e.get("kind") == "serve_request"]
    if serve_events:
        trace.extend(_build_serve_trace(serve_events, events))
    for e in events:
        if not isinstance(e, dict) or e.get("kind") in (
                "span", "serve_request"):
            continue
        state = e.get("state")
        task_id = e.get("task_id")
        if state == "RUNNING":
            starts[task_id] = e
            continue
        if state not in ("FINISHED", "FAILED"):
            continue
        s = starts.pop(task_id, None)
        ph = e.get("phases") or _EMPTY
        owner_pid = (e.get("worker_id") or "")[:8]
        exec_pid = (ph[IDX_WORKER] or e.get("worker_id") or "")[:8]
        name = e.get("name", "")
        task_ts = task_end = None
        if s is not None:
            task_ts = s["time"] * 1e6
        else:
            # Coalesced flush dropped the RUNNING row (the terminal event
            # carries the full phase record instead): the slice starts at
            # the dispatch/receive stamp.
            start = ph[PH_DISPATCHED] or ph[PH_RECEIVED]
            if start is not None:
                task_ts = start * 1e6
        if task_ts is not None:
            task_end = max(e["time"] * 1e6, task_ts)
            trace.append({
                "cat": "task", "name": name, "ph": "X",
                "ts": task_ts, "dur": task_end - task_ts,
                "pid": exec_pid, "tid": 0, "state": state,
                "task_id": task_id,
            })
        for sub_name, a, b in SUB_SLICES:
            ta, tb = ph[a], ph[b]
            if ta is None or tb is None:
                continue
            ts, end = ta * 1e6, max(ta, tb) * 1e6
            if task_ts is not None:
                # Nest inside the task slice (clock skew must not push a
                # sub-slice outside its parent).
                ts = min(max(ts, task_ts), task_end)
                end = min(max(end, ts), task_end)
            trace.append({
                "cat": "phase", "name": sub_name, "ph": "X",
                "ts": ts, "dur": end - ts,
                "pid": exec_pid, "tid": 1, "task_id": task_id,
            })
        submitted = ph[PH_SUBMITTED]
        if submitted is None:
            continue
        sub_ts = submitted * 1e6
        dispatch_end = max(
            sub_ts, (ph[PH_DISPATCHED] or submitted) * 1e6)
        trace.append({
            "cat": "phase", "name": "submit", "ph": "X",
            "ts": sub_ts, "dur": dispatch_end - sub_ts,
            "pid": owner_pid, "tid": 0, "task_id": task_id,
        })
        exec_ts = ph[PH_EXEC_START]
        flow_end = (exec_ts * 1e6 if exec_ts is not None else task_ts)
        if flow_end is None:
            continue
        trace.append({
            "cat": "flow", "name": "task_flow", "ph": "s", "id": task_id,
            "ts": sub_ts, "pid": owner_pid, "tid": 0,
            "task_id": task_id,
        })
        trace.append({
            "cat": "flow", "name": "task_flow", "ph": "f", "bp": "e",
            "id": task_id, "ts": max(flow_end, sub_ts), "pid": exec_pid,
            "tid": 0, "task_id": task_id,
        })
    return trace


def _build_serve_trace(serve_events: List[dict],
                       all_events: List[dict]) -> List[dict]:
    """Chrome-trace rows for serve requests: one trace per request id
    crossing every pid the request touched.

    Per `kind:"serve_request"` event (one per hop — proxy, replica,
    replay marker) this emits an enclosing hop slice on that process's
    lane, per-phase sub-slices, and flow arrows proxy -> replica keyed
    by the request id. Spans whose trace_id belongs to a serve request
    (the root request span, the replica exec span, and any task/nested
    spans the handler spawned — they inherit the trace through
    TaskSpec.trace_ctx) are drawn as `serve_span` slices on THEIR
    recording pid, which is what stitches proxy, replica, and spawned-
    task processes into one trace."""
    out: List[dict] = []
    by_req: Dict[str, list] = {}
    for e in serve_events:
        rid = e.get("request_id")
        if rid:
            by_req.setdefault(rid, []).append(e)
    for rid, evs in by_req.items():
        for e in evs:
            hop = e.get("hop", "")
            pid = str(e.get("pid", ""))
            dep = e.get("deployment", "")
            ph = e.get("phases") or [None] * REQ_RECORD_LEN
            if hop == "replay":
                out.append({
                    "cat": "serve", "name": "replay", "ph": "i",
                    "ts": e.get("time", 0.0) * 1e6, "pid": pid, "tid": 0,
                    "s": "p", "request_id": rid, "deployment": dep,
                })
                continue
            present = [(t, REQ_PHASE_ORDER[i])
                       for i, t in enumerate(ph) if t is not None]
            present.sort()
            if not present:
                continue
            ts = present[0][0] * 1e6
            end = max(present[-1][0] * 1e6, ts)
            hop_slice = {
                "cat": "serve", "name": f"{hop}:{dep}", "ph": "X",
                "ts": ts, "dur": end - ts, "pid": pid, "tid": 0,
                "request_id": rid, "deployment": dep, "hop": hop,
            }
            if e.get("replays"):
                hop_slice["replays"] = e["replays"]
            out.append(hop_slice)
            for (t0, _n0), (t1, n1) in zip(present, present[1:]):
                out.append({
                    "cat": "serve_phase", "name": n1, "ph": "X",
                    "ts": t0 * 1e6,
                    "dur": max(0.0, (t1 - t0)) * 1e6,
                    "pid": pid, "tid": 1, "request_id": rid,
                })
        # Flow arrows: proxy dispatch -> each replica exec_start.
        proxies = [e for e in evs if e.get("hop") == "proxy"]
        replicas = [e for e in evs if e.get("hop") == "replica"]
        if proxies and replicas:
            p = proxies[0]
            pph = p.get("phases") or []
            src = None
            if len(pph) > RQ_DISPATCH and pph[RQ_DISPATCH] is not None:
                src = pph[RQ_DISPATCH]
            elif len(pph) > RQ_PROXY_RECV:
                src = pph[RQ_PROXY_RECV]
            if src is not None:
                out.append({
                    "cat": "serve_flow", "name": "request", "ph": "s",
                    "id": "req:" + rid, "ts": src * 1e6,
                    "pid": str(p.get("pid", "")), "tid": 0,
                    "request_id": rid,
                })
                for r in replicas:
                    rph = r.get("phases") or []
                    dst = next((rph[i] for i in (RQ_EXEC_START,
                                                 RQ_ADMISSION)
                                if len(rph) > i and rph[i] is not None),
                               None)
                    if dst is None:
                        continue
                    out.append({
                        "cat": "serve_flow", "name": "request", "ph": "f",
                        "bp": "e", "id": "req:" + rid,
                        "ts": max(dst, src) * 1e6,
                        "pid": str(r.get("pid", "")), "tid": 0,
                        "request_id": rid,
                    })
    # Spans belonging to serve traces: drawn here (build_trace skips
    # spans otherwise) so the handler's spawned tasks / nested calls
    # appear in the same chrome trace on their own pids.
    for e in all_events:
        if not isinstance(e, dict) or e.get("kind") != "span":
            continue
        tid = e.get("trace_id")
        if tid not in by_req or e.get("end") is None:
            continue
        out.append({
            "cat": "serve_span", "name": e.get("name", ""), "ph": "X",
            "ts": e["start"] * 1e6,
            "dur": max(0.0, e["end"] - e["start"]) * 1e6,
            "pid": str(e.get("pid", "")), "tid": 2,
            "request_id": tid, "span_id": e.get("span_id"),
            "parent_id": e.get("parent_id"),
        })
    return out


def latency_summary(events: List[dict]) -> List[dict]:
    """Per-(task name, phase) p50/p95 rows from task events with phases:
    the data behind `ray_tpu summary`'s latency table and the dashboard
    Latency panel."""
    acc: Dict[Tuple[str, str], List[float]] = {}
    for e in events:
        if not isinstance(e, dict):
            continue
        ph = e.get("phases")
        if not ph:
            continue
        if e.get("kind") == "serve_request":
            # Serve request hops fold under "serve:<deployment>" so the
            # same latency table covers tasks AND requests.
            name = "serve:" + e.get("deployment", "")
            for phase, d in request_phase_durations(ph):
                acc.setdefault((name, phase), []).append(d)
            continue
        name = e.get("name", "")
        for phase, d in phase_durations(ph):
            acc.setdefault((name, phase), []).append(d)
    rows = []
    for (name, phase), ds in sorted(acc.items()):
        ds.sort()
        n = len(ds)
        # Nearest-rank percentiles: ceil(q*n)-1. (int(q*n) is one rank
        # too high — for n<=20 it reports the sample MAX as the p95.)
        p50 = ds[max(0, -(-n // 2) - 1)]
        p95 = ds[max(0, -(-(n * 19) // 20) - 1)]
        rows.append({
            "name": name, "phase": phase, "count": n,
            "p50_ms": round(p50 * 1e3, 3),
            "p95_ms": round(p95 * 1e3, 3),
        })
    return rows
