"""Worker fork-server (zygote): pay the interpreter + framework import cost
once per node, then fork workers in milliseconds.

The reference hides worker startup latency by prestarting idle worker
processes in the raylet's WorkerPool (src/ray/raylet/worker_pool.h). In this
environment a cold ``python`` start costs seconds (sitecustomize registers the
TPU PJRT plugin, importing jax), which serializes badly on small CI boxes —
so we go further: one warm template process per raylet that ``fork()``s a
worker per request. Children inherit the warmed import state but create their
own event loop and RPC connections; no threads or event loops exist in the
template at fork time, so the fork is safe.

Protocol (line-delimited JSON over stdin/stdout):
  raylet -> forkserver: {"spawn": {"env": {...}, "log_path": "..."}}
  forkserver -> raylet: {"event": "ready"}
                        {"event": "spawned", "pid": N, "worker_id": "..."}
                        {"event": "exit", "pid": N, "worker_id": "...",
                         "status": N}
On stdin EOF (raylet death) the forkserver kills its children and exits.
"""

from __future__ import annotations

import json
import os
import select
import signal
import sys


def _send(msg: dict) -> None:
    sys.stdout.write(json.dumps(msg) + "\n")
    sys.stdout.flush()


def _run_child(req: dict) -> None:
    """Forked child: detach, redirect output, become a worker. Never returns."""
    try:
        os.setsid()
    except OSError:
        pass
    log_path = req.get("log_path")
    if log_path:
        fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(fd, 1)
        os.dup2(fd, 2)
        if fd > 2:
            os.close(fd)
    devnull = os.open(os.devnull, os.O_RDONLY)
    os.dup2(devnull, 0)
    if devnull > 2:
        os.close(devnull)
    # Reset to exactly the requested env: the template's env belongs to the
    # raylet that started the zygote and may be stale for this spawn.
    env = req.get("env", {})
    if env:
        os.environ.clear()
        os.environ.update(env)
        # os.environ alone doesn't retrofit sys.path — the zygote built its
        # path from the PYTHONPATH it was STARTED with. Prepend any request
        # PYTHONPATH entries the zygote didn't have (same staleness class
        # as the env reset above).
        for p in reversed(env.get("PYTHONPATH", "").split(os.pathsep)):
            if p and p not in sys.path:
                sys.path.insert(0, p)
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    try:
        from ray_tpu._private import worker_main
        worker_main.main()
    except SystemExit:
        pass
    except BaseException:
        import traceback
        traceback.print_exc()
    finally:
        os._exit(0)


def main() -> None:
    # Warm the worker's import tree while we are still single-threaded.
    import ray_tpu._private.worker_main  # noqa: F401
    import ray_tpu._private.serialization  # noqa: F401

    children: dict = {}  # pid -> worker_id hex
    _send({"event": "ready"})
    stdin_fd = sys.stdin.fileno()
    buf = b""
    eof = False
    while True:
        try:
            readable, _, _ = select.select([stdin_fd], [], [], 0.2)
        except InterruptedError:
            readable = []
        # Reap exited children and report them.
        while children:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                break
            if pid == 0:
                break
            wid = children.pop(pid, None)
            _send({"event": "exit", "pid": pid, "worker_id": wid,
                   "status": status})
        if eof and not children:
            return
        if not readable or eof:
            continue
        chunk = os.read(stdin_fd, 1 << 16)
        if not chunk:
            # Raylet died or closed us: terminate children, drain, exit.
            eof = True
            for pid in list(children):
                try:
                    os.kill(pid, signal.SIGTERM)
                except OSError:
                    pass
            continue
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if not line.strip():
                continue
            try:
                req = json.loads(line)
            except ValueError:
                continue
            spawn = req.get("spawn")
            if spawn is None:
                continue
            pid = os.fork()
            if pid == 0:
                _run_child(spawn)  # never returns
            wid = spawn.get("env", {}).get("RAY_TPU_WORKER_ID", "")
            children[pid] = wid
            _send({"event": "spawned", "pid": pid, "worker_id": wid})


if __name__ == "__main__":
    main()
