"""Worker fork-server (zygote): pay the interpreter + framework import cost
once per node, then fork workers in milliseconds.

The reference hides worker startup latency by prestarting idle worker
processes in the raylet's WorkerPool (src/ray/raylet/worker_pool.h). In this
environment a cold ``python`` start costs seconds (sitecustomize registers the
TPU PJRT plugin, importing jax), which serializes badly on small CI boxes —
so we go further: one warm template process per raylet that ``fork()``s a
worker per request. Children inherit the warmed import state but create their
own event loop and RPC connections; no threads or event loops exist in the
template at fork time, so the fork is safe.

Protocol (line-delimited JSON over stdin/stdout):
  raylet -> forkserver: {"spawn": {"env": {...}, "log_path": "..."}}
                        {"spawn_batch": [{"env": ..., "log_path": ...}, ...]}
  forkserver -> raylet: {"event": "ready"}
                        {"event": "spawned", "pid": N, "worker_id": "..."}
                        {"event": "exit", "pid": N, "worker_id": "...",
                         "status": N}
A `spawn_batch` line forks every requested child back to back (launch
storms pay one pipe write + one template wakeup for N workers, not N).
On stdin EOF (raylet death) the forkserver kills its children and exits.
"""

from __future__ import annotations

import json
import os
import select
import signal
import sys


def _send(msg: dict) -> None:
    sys.stdout.write(json.dumps(msg) + "\n")
    sys.stdout.flush()


def _run_child(req: dict) -> None:
    """Forked child: detach, redirect output, become a worker. Never returns."""
    try:
        os.setsid()
    except OSError:
        pass
    log_path = req.get("log_path")
    if log_path:
        fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(fd, 1)
        os.dup2(fd, 2)
        if fd > 2:
            os.close(fd)
    devnull = os.open(os.devnull, os.O_RDONLY)
    os.dup2(devnull, 0)
    if devnull > 2:
        os.close(devnull)
    # Reset to exactly the requested env: the template's env belongs to the
    # raylet that started the zygote and may be stale for this spawn.
    env = req.get("env", {})
    if env:
        os.environ.clear()
        os.environ.update(env)
        # os.environ alone doesn't retrofit sys.path — the zygote built its
        # path from the PYTHONPATH it was STARTED with. Prepend any request
        # PYTHONPATH entries the zygote didn't have (same staleness class
        # as the env reset above).
        for p in reversed(env.get("PYTHONPATH", "").split(os.pathsep)):
            if p and p not in sys.path:
                sys.path.insert(0, p)
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    # Every child forked from the template inherits the SAME PRNG state
    # (and object addresses — see the pool/serve tests): reseed so
    # worker-side random choices (jitter, sampling) don't march in
    # lockstep across the fleet.
    import random
    random.seed()
    try:
        import numpy as _np
        _np.random.seed()
    except Exception:  # noqa: BLE001 — numpy is optional here
        pass
    try:
        from ray_tpu._private import worker_main
        worker_main.main()
    except SystemExit:
        pass
    except BaseException:
        import traceback
        traceback.print_exc()
    finally:
        os._exit(0)


def _warm_imports() -> None:
    """Pre-import the worker's heavy module set while still
    single-threaded, so fork->register is import-free in the child.
    worker_main's own top-level imports are light (its heavy deps load
    inside main()), so name the hot ones explicitly; each is
    best-effort — a missing optional dep must not kill the zygote."""
    for mod in ("ray_tpu._private.worker_main",
                "ray_tpu._private.serialization",
                "ray_tpu._private.core_worker",
                "ray_tpu._private.rpc",
                "ray_tpu._private.config",
                "ray_tpu._private.object_store",
                "ray_tpu._private.runtime_env",
                "ray_tpu.dag.compiled",
                "ray_tpu.exceptions",
                "numpy",
                # worker_main mirrors JAX_PLATFORMS into jax.config per
                # child; without the template import every forked child
                # pays the full (~0.6s) jax import serially on a loaded
                # box. Import only — backend init stays lazy, so no
                # threads exist at fork time.
                "jax"):
        try:
            __import__(mod)
        except Exception:  # noqa: BLE001
            pass


def _fork_one(spawn: dict, children: dict) -> None:
    pid = os.fork()
    if pid == 0:
        _run_child(spawn)  # never returns
    wid = spawn.get("env", {}).get("RAY_TPU_WORKER_ID", "")
    children[pid] = wid
    _send({"event": "spawned", "pid": pid, "worker_id": wid})


def main() -> None:
    _warm_imports()
    # Freeze the preloaded heap before serving forks: children inherit
    # the template's object graph (jax + the worker module set, hundreds
    # of thousands of objects), and without this every gen-2 GC pass in
    # every forked worker re-traverses it — measured as a ~50-75 ms
    # stop-the-world stall that made the n:n actor-call smoke row
    # bimodal (slow mode = a burst that contained one such pass). The
    # permanent generation survives fork, so one freeze here covers the
    # whole fleet; it also keeps copy-on-write pages shared (gc touches
    # refcount-adjacent GC headers when it scans).
    import gc
    gc.collect()
    gc.freeze()

    children: dict = {}  # pid -> worker_id hex
    _send({"event": "ready"})
    stdin_fd = sys.stdin.fileno()
    buf = b""
    eof = False
    while True:
        try:
            readable, _, _ = select.select([stdin_fd], [], [], 0.2)
        except InterruptedError:
            readable = []
        # Reap exited children and report them.
        while children:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                break
            if pid == 0:
                break
            wid = children.pop(pid, None)
            _send({"event": "exit", "pid": pid, "worker_id": wid,
                   "status": status})
        if eof and not children:
            return
        if not readable or eof:
            continue
        chunk = os.read(stdin_fd, 1 << 16)
        if not chunk:
            # Raylet died or closed us: terminate children, drain, exit.
            eof = True
            for pid in list(children):
                try:
                    os.kill(pid, signal.SIGTERM)
                except OSError:
                    pass
            continue
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if not line.strip():
                continue
            try:
                req = json.loads(line)
            except ValueError:
                continue
            batch = req.get("spawn_batch")
            if batch is None:
                spawn = req.get("spawn")
                batch = [spawn] if spawn is not None else []
            for spawn in batch:
                _fork_one(spawn, children)


if __name__ == "__main__":
    main()
