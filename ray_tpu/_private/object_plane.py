"""Object-plane facade: one routing layer over the node-local shm store.

The store itself (object_store.py) is mechanism — segments, entries, pins.
This module is POLICY: every subsystem that moves large payloads (core
put/get, serve request/response bodies, streaming-ingest blocks, podracer
weight broadcasts, compiled-DAG store channels) decides "inline or plane?"
here, against one set of size thresholds, and wraps its bytes so they ride
pickle-5 out-of-band buffers — written straight into a shm segment on put
and handed back as pinned zero-copy views on a same-node get.

Static enforcement: scripts/check_store_routing.py walks the producer
paths and fails if any of them serializes a large payload over a raw RPC
frame instead of calling through this module.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional, Tuple

# ---------------------------------------------------------------------------
# Size thresholds (bytes). Everything at or above the threshold for its
# path goes through the shm store; below it rides inline in the RPC frame
# (a store round-trip costs two RPCs — for tiny payloads the frame wins).
# ---------------------------------------------------------------------------

_DEFAULTS = {
    # Task args / returns / ray_tpu.put — matches
    # config.max_direct_call_object_size (the reference's inline cutover).
    "task": 100 * 1024,
    # HTTP bodies: the proxy<->replica hop copies the body once per RPC
    # frame; above 1MB the store's single shm write wins.
    "serve_body": 1 << 20,
    # Streaming-ingest blocks queued between producer and consumer.
    "ingest_block": 1 << 20,
    # Podracer weight broadcasts (per-version, fanned out to every gang
    # member on the node).
    "weights": 4 << 20,
    # Compiled-DAG StoreChannel messages: above this the KV carries only
    # the control word and the payload rides the store.
    "dag_channel": 64 << 10,
}


def threshold(kind: str = "task", default: Optional[int] = None) -> int:
    """Size threshold for a routing path, env-overridable per kind
    (RAY_TPU_PLANE_THRESHOLD_SERVE_BODY=...) or globally
    (RAY_TPU_OBJECT_PLANE_THRESHOLD). `default` lets a caller carry a
    configured value (e.g. config.max_direct_call_object_size) that the
    env overrides but the table default does not."""
    env = os.environ.get(f"RAY_TPU_PLANE_THRESHOLD_{kind.upper()}")
    if env is None:
        env = os.environ.get("RAY_TPU_OBJECT_PLANE_THRESHOLD")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    if default is not None:
        return default
    return _DEFAULTS.get(kind, _DEFAULTS["task"])


# ---------------------------------------------------------------------------
# Zero-copy payload wrapper.
# ---------------------------------------------------------------------------

class SharedPayload:
    """Bytes-like wrapper that serializes OUT-OF-BAND (pickle protocol 5).

    A plain ``bytes`` value pickles in-band: it is copied into the pickle
    stream on serialize and copied out again on loads — two full-body
    copies per hop. Wrapping the body makes it a PickleBuffer, which the
    serializer keeps as a raw buffer: the store client writes it directly
    into the shm segment, and a same-node reader deserializes it as a
    memoryview INTO the segment (no copy at all until someone asks for
    ``bytes(payload)``).

    The view stays valid for as long as the deserialized object's store
    pin is held (core_worker keeps the pin while any materialized value
    from that object is alive); callers that need the data past the
    value's lifetime must copy via ``to_bytes()``.
    """

    __slots__ = ("_buf",)

    def __init__(self, data):
        if isinstance(data, SharedPayload):
            data = data._buf
        self._buf = data if isinstance(data, memoryview) else memoryview(data)

    # -- pickle-5 out-of-band plumbing --
    def __reduce_ex__(self, protocol):
        if protocol >= 5:
            return (SharedPayload, (pickle.PickleBuffer(self._buf),))
        return (SharedPayload, (bytes(self._buf),))

    # -- bytes-like surface --
    @property
    def view(self) -> memoryview:
        return self._buf

    def to_bytes(self) -> bytes:
        return bytes(self._buf)

    def __bytes__(self) -> bytes:
        return bytes(self._buf)

    def __len__(self) -> int:
        return self._buf.nbytes

    def __buffer__(self, flags):  # Python 3.12 buffer protocol
        return self._buf

    def __eq__(self, other) -> bool:
        if isinstance(other, SharedPayload):
            return self._buf == other._buf
        if isinstance(other, (bytes, bytearray, memoryview)):
            return self._buf == other
        return NotImplemented

    def __hash__(self):
        return hash(bytes(self._buf))

    def __repr__(self) -> str:
        return f"SharedPayload({self._buf.nbytes} bytes)"


def wrap_body(data, kind: str = "serve_body"):
    """Route a bytes payload: SharedPayload (out-of-band, plane) when at or
    above the threshold for `kind`, unchanged otherwise."""
    if isinstance(data, SharedPayload):
        return data
    if isinstance(data, (bytes, bytearray, memoryview)) and \
            len(data) >= threshold(kind):
        return SharedPayload(data)
    return data


def body_view(data) -> memoryview:
    """Zero-copy view of a body regardless of wrapping."""
    if isinstance(data, SharedPayload):
        return data.view
    return memoryview(data)


def body_bytes(data) -> bytes:
    """Materialize a body to plain bytes (copies if wrapped)."""
    if isinstance(data, (bytes, type(None))):
        return data or b""
    if isinstance(data, SharedPayload):
        return data.to_bytes()
    return bytes(data)


# ---------------------------------------------------------------------------
# Ref-based offload for queue/broadcast paths (ingest blocks, weights).
# ---------------------------------------------------------------------------

class PlaneRef:
    """Marker carrying an ObjectRef through a queue/control message so the
    consumer knows to resolve it from the plane (vs a literal value)."""

    __slots__ = ("ref",)

    def __init__(self, ref):
        self.ref = ref


def _approx_size(value) -> int:
    """Cheap size probe for offload decisions — exact for buffers, nbytes
    for arrays, 0 (never offload) for anything unsized."""
    if isinstance(value, SharedPayload):
        return len(value)
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    nbytes = getattr(value, "nbytes", None)
    if isinstance(nbytes, int):
        return nbytes
    return 0


def maybe_offload(value, kind: str) -> Any:
    """Put `value` into the object plane when it is large, returning a
    PlaneRef; small/unsized values pass through untouched."""
    if isinstance(value, PlaneRef):
        return value
    if _approx_size(value) >= threshold(kind):
        from ray_tpu._private import worker_api
        return PlaneRef(worker_api.put(value))
    return value


def resolve(item, timeout: Optional[float] = None) -> Any:
    """Inverse of maybe_offload: fetch a PlaneRef's value (zero-copy view
    for arrays/wrapped bytes on the same node), pass literals through."""
    if isinstance(item, PlaneRef):
        from ray_tpu._private import worker_api
        return worker_api.get(item.ref, timeout)
    return item


def put_object(value: Any):
    """Plane put from any thread; returns an ObjectRef."""
    from ray_tpu._private import worker_api
    return worker_api.put(value)


def get_object(ref, timeout: Optional[float] = None) -> Any:
    """Plane get from any thread (zero-copy for same-node large buffers)."""
    from ray_tpu._private import worker_api
    return worker_api.get(ref, timeout)
