"""Deterministic binary IDs for jobs/tasks/objects/actors/nodes.

Capability parity with the reference's ID scheme (src/ray/common/id.h): IDs are
fixed-size random/derived byte strings with cheap hashing and hex round-trip.
Derivation rules (ObjectID = TaskID + return index; ActorID embeds JobID) follow
the same *semantics* without copying the bit layout.
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading

_NIL = b"\x00"


class BaseID:
    SIZE = 16
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} expects {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = id_bytes
        self._hash = hash(id_bytes)

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(_NIL * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == _NIL * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, i: int):
        return cls(i.to_bytes(4, "little"))

    def int(self) -> int:
        return int.from_bytes(self._bytes, "little")


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    """12 random bytes + 4-byte JobID suffix."""

    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID):
        return cls(os.urandom(12) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[12:])


class TaskID(BaseID):
    """16 random/derived bytes + 4-byte JobID suffix."""

    SIZE = 20

    @classmethod
    def of(cls, job_id: JobID):
        return cls(os.urandom(16) + job_id.binary())

    @classmethod
    def for_index(cls, job_id: JobID, seed: bytes, index: int):
        """Deterministic per-submitter id: 8 seed bytes + 8 counter bytes.

        Avoids an os.urandom syscall on the submission hot path (reference
        derives TaskIDs from parent task + counter the same way,
        src/ray/common/id.h). One fused pack: the slice+pack+concat chain
        was three allocations per submitted task ("8s" truncates a longer
        seed)."""
        return cls(struct.pack("<8sQ4s", seed, index, job_id.binary()))

    @classmethod
    def for_actor_task(cls, job_id: JobID, actor_id: ActorID, seq: int,
                       epoch: int = 0):
        # epoch (actor restart count at submission) keeps post-restart task
        # ids distinct from pre-restart ones after seq renumbering.
        h = hashlib.blake2b(
            actor_id.binary() + seq.to_bytes(8, "little")
            + epoch.to_bytes(4, "little"), digest_size=16
        ).digest()
        return cls(h + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[16:])


class ObjectID(BaseID):
    """TaskID (20 bytes) + 4-byte little-endian return index."""

    SIZE = 24

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int):
        return cls(struct.pack("<20sI", task_id.binary(), index))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        # Put objects use the high bit of the index to avoid collision with returns.
        return cls(struct.pack("<20sI", task_id.binary(),
                               put_index | 0x80000000))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:20])

    def job_id(self) -> JobID:
        return self.task_id().job_id()


class PlacementGroupID(BaseID):
    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID):
        return cls(os.urandom(12) + job_id.binary())


class _Counter:
    __slots__ = ("_v", "_lock")

    def __init__(self, start: int = 0):
        self._v = start
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._v += 1
            return self._v
