"""Cluster time-series store and the delta-frame codec that feeds it.

The telemetry plane has three pieces:

- ``FrameEncoder`` runs inside every daemon-hosting process (the
  MetricsAgent side).  Each report tick it diffs the local registry
  snapshot against what it last shipped and emits a *delta frame*:
  only changed series, with the (name, tags) tuple interned to a small
  integer on first ship so steady-state frames are a handful of
  ``[id, value]`` rows.  Rows carry **absolute** cumulative values, not
  deltas — replaying a frame is idempotent, and all reset/restart
  accounting happens once, server-side.
- ``FrameDecoder`` runs on the GCS, one per reporter.  It reconstructs
  the reporter's full current snapshot (so the merged Prometheus view
  keeps working) and returns the changed rows for TSDB ingest.  An
  unknown intern id (GCS restarted, or the agent outlived a decoder
  eviction) raises ``ResyncNeeded`` and the agent re-ships definitions.
- ``TSDB`` is the GCS-side store: one fixed-slot ring per series
  (``retention_s / resolution_s`` slots), bounded cardinality with a
  drop counter, and per-(series, reporter) counter-reset clamping — the
  DeploymentSLO restart-clamp logic generalized: first sight of a
  reporter records a baseline without charging, a negative delta means
  the process restarted and the new absolute is charged in full.

Queries return window-aligned points (slot timestamps are multiples of
the resolution) with ``value``/``rate``/``mean``/``p50``/``p95``/``p99``
folds; percentiles are derived from the shipped histogram buckets by
linear interpolation within the covering bucket.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple


class ResyncNeeded(Exception):
    """Decoder saw an intern id it has no definition for."""


# ---------------------------------------------------------------------------
# Delta-frame codec
# ---------------------------------------------------------------------------


class FrameEncoder:
    """Delta-encodes registry snapshots for shipping (agent side)."""

    def __init__(self):
        self._ids: Dict[Tuple[str, tuple], int] = {}
        self._last: Dict[int, Any] = {}
        self._next = 0

    def reset(self) -> None:
        """Forget everything shipped; the next frame re-sends definitions."""
        self._ids.clear()
        self._last.clear()
        self._next = 0

    def encode(self, metrics: Sequence[dict]) -> Optional[dict]:
        """Diff ``metrics`` (a registry snapshot) against the last ship.

        Returns a frame dict or ``None`` when nothing changed.
        """
        defs: Dict[int, list] = {}
        rows: List[list] = []
        for m in metrics:
            tags = m.get("tags") or {}
            key = (m["name"], tuple(sorted(tags.items())))
            sid = self._ids.get(key)
            fresh = sid is None
            if fresh:
                sid = self._next
                self._next += 1
                self._ids[key] = sid
                defs[sid] = [m["name"], m.get("type", "gauge"),
                             sorted(tags.items()),
                             m.get("description", ""),
                             list(m.get("bounds") or [])]
            if m.get("type") == "histogram":
                state = (tuple(m["bucket_counts"]), m["sum"], m["count"])
                if not fresh and self._last.get(sid) == state:
                    continue
                self._last[sid] = state
                rows.append([sid, list(state[0]), state[1], state[2]])
            else:
                v = m.get("value", 0)
                if not fresh and self._last.get(sid) == v:
                    continue
                self._last[sid] = v
                rows.append([sid, v])
        if not rows and not defs:
            return None
        return {"defs": defs, "rows": rows}


class FrameDecoder:
    """Reconstructs one reporter's snapshot from delta frames (GCS side)."""

    def __init__(self):
        self.series: Dict[int, dict] = {}

    def decode(self, frame: dict) -> List[dict]:
        """Apply a frame; returns the changed metric dicts (live refs)."""
        for sid, d in (frame.get("defs") or {}).items():
            sid = int(sid)
            name, typ, tags, desc, bounds = d
            m = {"name": name, "type": typ, "description": desc,
                 "tags": dict(tags)}
            if typ == "histogram":
                m["bounds"] = list(bounds)
                m["bucket_counts"] = [0] * (len(bounds) + 1)
                m["sum"] = 0.0
                m["count"] = 0
            else:
                m["value"] = 0
            self.series[sid] = m
        changed: List[dict] = []
        for row in frame.get("rows") or []:
            m = self.series.get(row[0])
            if m is None:
                raise ResyncNeeded(row[0])
            if m["type"] == "histogram":
                m["bucket_counts"] = list(row[1])
                m["sum"] = row[2]
                m["count"] = row[3]
            else:
                m["value"] = row[1]
            changed.append(m)
        return changed

    def snapshot(self) -> List[dict]:
        out = []
        for m in self.series.values():
            c = dict(m)
            if c["type"] == "histogram":
                c["bucket_counts"] = list(c["bucket_counts"])
            out.append(c)
        return out


# ---------------------------------------------------------------------------
# Time-series store
# ---------------------------------------------------------------------------

_PCT = {"p50": 0.50, "p95": 0.95, "p99": 0.99}


class _Series:
    __slots__ = ("name", "type", "tags", "bounds",
                 "vals", "stamps", "last_idx", "first_idx",
                 "cum", "hcounts", "hsum", "hcount",
                 "per_rep", "last_write_t")

    def __init__(self, name: str, typ: str, tags: tuple, nslots: int,
                 bounds: Optional[list]):
        self.name = name
        self.type = typ
        self.tags = tags
        self.bounds = list(bounds) if bounds else None
        self.vals: List[Any] = [None] * nslots
        self.stamps: List[int] = [-1] * nslots
        self.last_idx = -1
        self.first_idx = -1
        self.cum = 0.0
        self.hcounts: Optional[List[int]] = (
            [0] * (len(bounds) + 1) if bounds is not None else None)
        self.hsum = 0.0
        self.hcount = 0
        # reporter -> last absolute (counter), last value (gauge), or
        # (bucket_counts, sum, count) tuple (histogram) — the clamp state.
        self.per_rep: Dict[str, Any] = {}
        self.last_write_t = 0.0


class TSDB:
    """Ring-buffer time-series store with bounded cardinality."""

    def __init__(self, retention_s: float = 900.0, resolution_s: float = 5.0,
                 max_series: int = 8192):
        self.res = max(0.05, float(resolution_s))
        self.nslots = max(2, int(math.ceil(retention_s / self.res)))
        self.max_series = max_series
        self._series: Dict[Tuple[str, tuple], _Series] = {}
        self._lock = threading.Lock()
        self.dropped_total = 0

    @property
    def n_series(self) -> int:
        return len(self._series)

    # -- ingest ------------------------------------------------------------

    def ingest(self, reporter: str, metrics: Sequence[dict],
               now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            for m in metrics:
                self._ingest_one(reporter, m, now)

    def _ingest_one(self, reporter: str, m: dict, now: float) -> None:
        tags = m.get("tags") or {}
        key = (m["name"], tuple(sorted(tags.items())))
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= self.max_series:
                self.dropped_total += 1
                return
            s = _Series(m["name"], m.get("type", "gauge"), key[1],
                        self.nslots, m.get("bounds"))
            self._series[key] = s
        typ = s.type
        if typ == "histogram":
            counts = m.get("bucket_counts")
            if (counts is None or s.hcounts is None
                    or len(counts) != len(s.hcounts)):
                return
            state = (list(counts), float(m.get("sum", 0.0)),
                     int(m.get("count", 0)))
            prev = s.per_rep.get(reporter)
            s.per_rep[reporter] = state
            if prev is None:
                # First sight: baseline only (DeploymentSLO semantics).
                self._write(s, now)
                return
            dcount = state[2] - prev[2]
            if dcount < 0 or any(a < b for a, b in zip(state[0], prev[0])):
                # Process restarted: its counters began again from zero,
                # so the new absolutes are all post-restart activity.
                dcounts = state[0]
                dsum, dcount = state[1], state[2]
            else:
                dcounts = [a - b for a, b in zip(state[0], prev[0])]
                dsum = state[1] - prev[1]
            for i, d in enumerate(dcounts):
                s.hcounts[i] += d
            s.hsum += dsum
            s.hcount += dcount
        elif typ == "counter":
            v = float(m.get("value", 0))
            prev = s.per_rep.get(reporter)
            s.per_rep[reporter] = v
            if prev is None:
                self._write(s, now)
                return
            d = v - prev
            if d < 0:
                d = v
            s.cum += d
        else:  # gauge: level is the sum of each reporter's latest value
            s.per_rep[reporter] = float(m.get("value", 0))
            s.cum = sum(s.per_rep.values())
        self._write(s, now)

    def _write(self, s: _Series, now: float) -> None:
        idx = int(now // self.res)
        if s.first_idx < 0:
            s.first_idx = idx
        if s.last_idx >= 0 and idx > s.last_idx:
            # Carry the running cumulative forward over silent slots so
            # rate()/percentile folds see a flat step, not a hole.
            for j in range(s.last_idx + 1, idx):
                if idx - j >= self.nslots:
                    continue
                pos = j % self.nslots
                s.vals[pos] = s.vals[s.last_idx % self.nslots]
                s.stamps[pos] = j
        pos = idx % self.nslots
        if s.type == "histogram":
            s.vals[pos] = (tuple(s.hcounts), s.hsum, s.hcount)
        else:
            s.vals[pos] = s.cum
        s.stamps[pos] = idx
        s.last_idx = max(s.last_idx, idx)
        s.last_write_t = now

    def drop_reporter(self, reporter: str) -> None:
        """Forget a vanished reporter's clamp state (and gauge share)."""
        with self._lock:
            for s in self._series.values():
                if reporter in s.per_rep:
                    del s.per_rep[reporter]
                    if s.type == "gauge":
                        s.cum = sum(s.per_rep.values())

    # -- query -------------------------------------------------------------

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k in self._series})

    def query(self, name: str, tags: Optional[dict] = None,
              window_s: float = 300.0, fold: str = "value",
              now: Optional[float] = None) -> List[dict]:
        """Aligned-window query.

        Returns ``[{"name", "tags", "type", "points": [[t, v], ...]}]``,
        one entry per series whose tags are a superset of ``tags``.
        Point timestamps are multiples of the resolution.  ``latest``
        ignores alignment and returns the single most recent value.
        """
        now = time.time() if now is None else now
        want = tuple(sorted((tags or {}).items()))
        out: List[dict] = []
        with self._lock:
            for (sname, stags), s in self._series.items():
                if sname != name:
                    continue
                if want and not set(want).issubset(set(stags)):
                    continue
                out.append({"name": sname, "tags": dict(stags),
                            "type": s.type,
                            "points": self._fold_series(s, window_s, fold,
                                                        now)})
        return out

    def _fold_series(self, s: _Series, window_s: float, fold: str,
                     now: float) -> List[list]:
        if fold == "latest":
            if s.last_idx < 0:
                return []
            v = s.vals[s.last_idx % self.nslots]
            if s.type == "histogram":
                v = v[2]
            return [[s.last_write_t, v]]
        end_idx = int(now // self.res)
        n = min(self.nslots - 1, max(1, int(math.ceil(window_s / self.res))))
        pts: List[list] = []
        for idx in range(end_idx - n + 1, end_idx + 1):
            if idx < 0:
                continue
            pos = idx % self.nslots
            if s.stamps[pos] != idx:
                continue
            t = idx * self.res
            v = self._fold_point(s, idx, fold)
            if v is not None:
                pts.append([t, v])
        return pts

    def _prev_val(self, s: _Series, idx: int):
        """Value at idx-1, or the zero baseline for the first-ever slot."""
        ppos = (idx - 1) % self.nslots
        if s.stamps[ppos] == idx - 1:
            return s.vals[ppos]
        if idx == s.first_idx:
            if s.type == "histogram":
                return (tuple([0] * len(s.hcounts)), 0.0, 0)
            return 0.0
        return None

    def _fold_point(self, s: _Series, idx: int, fold: str):
        v = s.vals[idx % self.nslots]
        if fold in ("value", "raw"):
            return v[2] if s.type == "histogram" else v
        if fold == "rate":
            prev = self._prev_val(s, idx)
            if prev is None:
                return None
            if s.type == "histogram":
                return (v[2] - prev[2]) / self.res
            return (v - prev) / self.res
        if s.type != "histogram":
            return None
        prev = self._prev_val(s, idx)
        if prev is None:
            return None
        dcounts = [a - b for a, b in zip(v[0], prev[0])]
        dcount = v[2] - prev[2]
        if dcount <= 0:
            return None
        if fold == "mean":
            return (v[1] - prev[1]) / dcount
        q = _PCT.get(fold)
        if q is None:
            return None
        return _bucket_quantile(s.bounds, dcounts, dcount, q)


def _bucket_quantile(bounds: Sequence[float], dcounts: Sequence[int],
                     total: int, q: float) -> float:
    """Linear-interpolated quantile over histogram bucket deltas."""
    target = q * total
    cum = 0
    for j, c in enumerate(dcounts):
        if c <= 0:
            cum += max(0, c)
            continue
        if cum + c >= target:
            lower = bounds[j - 1] if j > 0 else 0.0
            upper = bounds[j] if j < len(bounds) else bounds[-1]
            frac = (target - cum) / c
            return lower + (upper - lower) * max(0.0, min(1.0, frac))
        cum += c
    return float(bounds[-1]) if bounds else 0.0
