"""Container runtime environments: workers spawned inside images.

Reference parity: python/ray/_private/runtime_env/container.py — a task or
actor declaring runtime_env={"container": {"image": ..., "run_options":
[...]}} executes in a worker process started INSIDE that container
(podman/docker), with the session dir and framework source bind-mounted
and the worker env passed through.

Runtime gate: neither podman nor docker ships in this image, so the
raylet checks runner availability at lease time and fails container
leases with an actionable error when absent. Tests (and exotic runtimes)
inject a runner via RAY_TPU_CONTAINER_RUNNER="module:attr" — a callable
(image, run_options, inner_argv, env, mounts) -> argv.
"""

from __future__ import annotations

import os
import shutil
import sys
from typing import Dict, List, Optional

_RUNNERS = ("podman", "docker")


def resolve_runner():
    """-> (name, builder) or None. builder(image, run_options, inner_argv,
    env, mounts) -> argv to Popen."""
    hook = os.environ.get("RAY_TPU_CONTAINER_RUNNER")
    if hook:
        import importlib
        mod_name, _, attr = hook.partition(":")
        return ("hook", getattr(importlib.import_module(mod_name), attr))
    for name in _RUNNERS:
        if shutil.which(name):
            return (name, _cli_builder(name))
    return None


def runner_available() -> bool:
    return resolve_runner() is not None


def _cli_builder(runner: str):
    def build(image: str, run_options: List[str], inner_argv: List[str],
              env: Dict[str, str], mounts: List[str]) -> List[str]:
        argv = [runner, "run", "--rm", "--network=host"]
        for m in mounts:
            argv += ["-v", f"{m}:{m}"]
        for k, v in env.items():
            argv += ["--env", f"{k}={v}"]
        argv += list(run_options or [])
        argv.append(image)
        argv += inner_argv
        return argv

    return build


def build_worker_command(container: dict, env: Dict[str, str],
                         session_dir: str,
                         python: Optional[str] = None) -> List[str]:
    """argv that starts a ray_tpu worker inside the container.

    Mounts: the session dir (logs, shm handshake files) and the framework
    source root, so the image only needs a compatible python. The worker
    dials the raylet over the host network.
    """
    resolved = resolve_runner()
    if resolved is None:
        raise RuntimeError(
            "container runtime env needs podman or docker on the node "
            "(or a RAY_TPU_CONTAINER_RUNNER hook); none found")
    _name, builder = resolved
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    inner = [python or container.get("python") or "python3",
             "-m", "ray_tpu._private.worker_main"]
    mounts = [session_dir, repo_root, "/dev/shm"]
    env = dict(env, PYTHONPATH=(repo_root + os.pathsep
                                + env.get("PYTHONPATH", "")).rstrip(
                                    os.pathsep))
    return builder(container["image"],
                   list(container.get("run_options") or []),
                   inner, env, mounts)
