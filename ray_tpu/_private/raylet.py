"""Raylet: the per-node daemon.

Capability parity with the reference raylet (src/ray/raylet/node_manager.h,
worker_pool.h, local_task_manager.h, scheduling/): worker lifecycle management,
the worker-lease protocol with distributed scheduling + spillback (each raylet
decides locally against a synced cluster resource view, forwarding the lease to
a better node when it has no capacity — hybrid pack/spread policy per
hybrid_scheduling_policy.h), placement-group bundle reservation
(bundle_scheduling_policy.h), the in-process shared-memory object store
(plasma runs inside the raylet in the reference too), node-to-node object
transfer (object_manager.h pull/push in chunks), and worker-death detection
feeding actor failover.
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu._private import rpc
from ray_tpu._private.common import NodeInfo, TaskSpec
from ray_tpu._private.config import Config
from ray_tpu._private.ids import NodeID, ObjectID, PlacementGroupID, WorkerID
from ray_tpu._private.object_store import ObjectStoreHost

logger = logging.getLogger(__name__)


class _SharedForkServer:
    """Process-wide zygote client (worker_forkserver.py).

    One warm template process serves every raylet in this OS process (the
    fake cluster runs many raylets per process) and survives across
    cluster setups, so only the first cluster in a test run pays the
    template's import cost. Spawn requests carry the per-worker env, so
    the template is raylet-agnostic.
    """

    _inst: Optional["_SharedForkServer"] = None

    def __init__(self):
        self.proc: Optional[asyncio.subprocess.Process] = None
        self.ready = False
        self.dead = False
        self.handlers: Dict[str, "Raylet"] = {}   # worker_id hex -> raylet
        self._starting = False
        self._ready_callbacks: List = []
        # Buffered before proc is up: (env, log_path, raylet) records —
        # kept structured (not pre-encoded bytes) so spawns that outlive
        # a dead zygote can fail over to Popen as a batch.
        self._pending_spawns: List[tuple] = []
        self._base_env: Optional[Dict[str, str]] = None

    @classmethod
    def get(cls) -> "_SharedForkServer":
        if cls._inst is None or cls._inst.dead:
            prev = cls._inst
            cls._inst = cls()
            if prev is not None:
                cls._inst._base_env = prev._base_env
        return cls._inst

    async def ensure_started(self, env: Dict[str, str]):
        if self.proc is not None or self._starting or self.dead:
            return
        self._base_env = dict(env)
        self._starting = True
        try:
            self.proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "ray_tpu._private.worker_forkserver",
                env=env,
                stdin=asyncio.subprocess.PIPE,
                stdout=asyncio.subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                start_new_session=True)
        except Exception:
            self.dead = True
            self._fail_pending()
            return
        finally:
            self._starting = False
        if self._pending_spawns:
            pending, self._pending_spawns = self._pending_spawns, []
            if not self._write_batch([(e, lp) for e, lp, _r in pending]):
                # The pipe died before the buffered spawns ever reached
                # the zygote: fail them over (as a batch) via Popen.
                self._pending_spawns = pending
                self._fail_pending()
                return
        asyncio.ensure_future(self._reader())

    def _write_batch(self, jobs: List[tuple]) -> bool:
        """One spawn_batch line for N workers; False if the pipe is gone."""
        import json
        line = (json.dumps({"spawn_batch": [
            {"env": env, "log_path": lp} for env, lp in jobs]}) + "\n"
        ).encode()
        try:
            self.proc.stdin.write(line)
        except Exception:
            self.dead = True
            return False
        return True

    async def _reader(self):
        import json
        proc = self.proc
        try:
            while True:
                line = await proc.stdout.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                event = msg.get("event")
                if event == "ready":
                    self.ready = True
                    for cb in self._ready_callbacks:
                        try:
                            cb()
                        except Exception:
                            pass
                    self._ready_callbacks.clear()
                elif event in ("spawned", "exit"):
                    raylet = self.handlers.get(msg.get("worker_id", ""))
                    if raylet is not None:
                        raylet._on_forkserver_event(event, msg)
                    if event == "exit":
                        self.handlers.pop(msg.get("worker_id", ""), None)
        finally:
            self.dead = True
            self.ready = False
            self._fail_pending()

    def _fail_pending(self):
        """Zygote died (or could not start). Spawns still BUFFERED here
        never reached it — their workers can still start, just without
        the warm fork: hand them back to their raylets as one batched
        Popen failover (one-by-one fallback was the old behavior; a
        launch storm buffered behind a dead zygote paid N serial
        round trips through the create timeout). Workers the zygote
        actually tracked are gone (or unknowable): report exits so
        supply accounting doesn't leak phantom handles."""
        pending, self._pending_spawns = self._pending_spawns, []
        by_raylet: Dict[int, tuple] = {}
        for env, log_path, raylet in pending:
            self.handlers.pop(env.get("RAY_TPU_WORKER_ID", ""), None)
            by_raylet.setdefault(id(raylet), (raylet, []))[1].append(
                (env, log_path))
        for raylet, jobs in by_raylet.values():
            try:
                raylet._popen_failover_batch(jobs)
            except Exception:
                logger.exception("batched Popen failover failed")
        for wid, raylet in list(self.handlers.items()):
            try:
                raylet._on_forkserver_event(
                    "exit", {"worker_id": wid, "pid": -1, "status": -1})
            except Exception:
                pass
        self.handlers.clear()

    def on_ready(self, cb):
        if self.ready:
            cb()
        else:
            self._ready_callbacks.append(cb)

    def spawn_many(self, jobs: List[tuple], raylet: "Raylet") -> bool:
        """Fork N workers with ONE request line (and one pipe write):
        `jobs` is [(env, log_path), ...]. All-or-nothing: False means no
        job was submitted and the caller should Popen-spawn instead."""
        if self.dead or not jobs:
            return not self.dead and not jobs
        if self.proc is None or self.proc.stdin is None:
            # Buffer (flushed on start). If no start is in flight — e.g.
            # this is a fresh instance replacing a dead zygote — kick one
            # off so buffered spawns don't sit forever.
            if not self._starting:
                if self._base_env is None:
                    return False  # nothing can start it: use Popen fallback
                asyncio.ensure_future(self.ensure_started(self._base_env))
            self._pending_spawns.extend(
                (env, log_path, raylet) for env, log_path in jobs)
        else:
            if not self._write_batch(jobs):
                return False
        for env, _log_path in jobs:
            self.handlers[env["RAY_TPU_WORKER_ID"]] = raylet
        return True


class PendingLease:
    """One queued worker-lease request with its per-spec scheduling keys
    resolved ONCE at enqueue. _try_dispatch / _ensure_worker_supply scan
    the pending list on every tick (and per grant); re-deriving
    env_hash / container-env / scheduling_class from the spec each scan
    was measurable overhead under a multi-client lease storm."""

    __slots__ = ("spec", "pg_key", "fut", "conn", "count", "env_hash",
                 "container_env", "sched_class", "demand_recorded")

    def __init__(self, spec, pg_key, fut, conn, count):
        self.spec = spec
        self.pg_key = pg_key
        self.fut = fut
        self.conn = conn
        self.count = count
        # Pool demand/miss accounting happens on the FIRST idle-worker
        # scan for this lease only; dispatch re-scans don't re-count.
        self.demand_recorded = False
        self.env_hash = spec.env_hash()
        env = getattr(spec, "runtime_env", None) or {}
        self.container_env = env if env.get("container") else None
        self.sched_class = spec.scheduling_class()


class WarmPools:
    """Env-hash-keyed idle worker pools with demand-sized floors.

    Replaces the flat idle list: a launch storm for one runtime env can
    no longer drain (or be starved by) another env's warm capacity, the
    reaper keeps a per-env floor sized by recent demand (EWMA of worker
    requests/s), and explicit `prestart_workers` hints — sent by the GCS
    ahead of gang restarts, serve scale-ups, and creation-batch fan-outs
    — pin a temporary floor so the pool is warm BEFORE the storm lands
    (reference: worker_pool.h PrestartWorkers + dedicated-worker pools
    per runtime env).
    """

    EWMA_HALFLIFE_S = 30.0
    # The demand floor holds enough warm workers to absorb this many
    # seconds of the recent request rate.
    DEMAND_WINDOW_S = 5.0
    # Demand-derived floors are a smoothing signal, not a license to hold
    # the node: they never exceed this per env (hints may).
    MAX_DEMAND_FLOOR = 16

    def __init__(self):
        self.pools: Dict[str, List["WorkerHandle"]] = {}
        self._rates: Dict[str, tuple] = {}   # env -> (EWMA req/s, stamp)
        # env -> (count, expires_at, fresh_alias). fresh_alias hints also
        # count toward the FRESH pool's floor (the generic workers they
        # prestart idle there until first lease).
        self._hints: Dict[str, tuple] = {}
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return sum(len(p) for p in self.pools.values())

    def sizes(self) -> Dict[str, int]:
        return {h: len(p) for h, p in self.pools.items() if p}

    def hash_list(self) -> List[str]:
        out: List[str] = []
        for h, p in self.pools.items():
            out.extend([h] * len(p))
        return out

    def put(self, handle: "WorkerHandle"):
        pool = self.pools.setdefault(handle.env_hash, [])
        if handle not in pool:
            pool.append(handle)

    def remove(self, handle: "WorkerHandle") -> bool:
        pool = self.pools.get(handle.env_hash)
        if pool and handle in pool:
            pool.remove(handle)
            return True
        # The handle may have been re-tagged after it went idle.
        for p in self.pools.values():
            if handle in p:
                p.remove(handle)
                return True
        return False

    def note_demand(self, env_hash: str, n: int = 1):
        """One worker-acquisition attempt for this env (feeds the EWMA
        floor the reaper respects)."""
        now = time.time()
        rate, ts = self._rates.get(env_hash, (0.0, now))
        if now > ts:
            rate *= 0.5 ** ((now - ts) / self.EWMA_HALFLIFE_S)
        self._rates[env_hash] = (rate + float(n), now)

    def hint(self, env_hash: str, count: int, ttl_s: float = 30.0,
             merge: bool = False, fresh_alias: bool = False):
        """Explicit prestart hint: hold at least `count` warm workers for
        `env_hash` until the hint expires (storms are announced, not
        inferred). merge=True keeps the max of this and any live hint —
        per-env max keeps a replayed hint RPC idempotent. fresh_alias
        hints ALSO count (summed across envs) toward the fresh pool's
        floor: the generic workers they prestart idle there until first
        lease, and two envs' batches must BOTH survive the reaper — a
        max would let it eat the second batch."""
        now = time.time()
        count = max(0, int(count))
        expires = now + ttl_s
        if merge:
            prev_count, prev_exp, prev_alias = self._hints.get(
                env_hash, (0, 0.0, False))
            if prev_exp > now:
                count = max(count, prev_count)
                expires = max(expires, prev_exp)
                fresh_alias = fresh_alias or prev_alias
        self._hints[env_hash] = (count, expires, fresh_alias)

    def floor(self, env_hash: str, fresh_floor: int = 0) -> int:
        """Reap-protection floor for one env pool: the fresh pool keeps
        the node's base prestart floor plus the sum of live fresh_alias
        hints; every pool keeps max(EWMA demand, live hint)."""
        now = time.time()
        hint_count, expires, _alias = self._hints.get(
            env_hash, (0, 0.0, False))
        if now >= expires:
            hint_count = 0
        if env_hash == "":
            hint_count += sum(
                c for h, (c, exp, alias) in self._hints.items()
                if h != "" and alias and exp > now)
        acc, ts = self._rates.get(env_hash, (0.0, now))
        acc *= 0.5 ** (max(0.0, now - ts) / self.EWMA_HALFLIFE_S)
        # `acc` is a decayed cumulative COUNT whose steady state is
        # rate * halflife/ln2 — convert to req/s, then hold enough warm
        # workers to absorb ~DEMAND_WINDOW_S of that rate. (Treating the
        # raw accumulator as a rate saturated the cap at <1 req/s and
        # pinned 16 jax-preloaded workers per env on light traffic.)
        est_rate = acc * 0.6931 / self.EWMA_HALFLIFE_S
        demand_floor = min(self.MAX_DEMAND_FLOOR,
                           int(est_rate * self.DEMAND_WINDOW_S + 0.5))
        base = fresh_floor if env_hash == "" else 0
        return max(base, demand_floor, hint_count)

    def prune(self):
        """Drop empty pools, expired hints, and fully decayed demand
        accumulators — a long-lived node serving many distinct runtime
        envs must not grow these dicts (and downstream per-env metric
        rows) forever."""
        now = time.time()
        for h in [h for h, p in self.pools.items() if not p and h != ""]:
            del self.pools[h]
        for h in [h for h, (_c, exp, _a) in self._hints.items()
                  if exp <= now]:
            del self._hints[h]
        for h in [h for h, (acc, ts) in self._rates.items()
                  if acc * 0.5 ** ((now - ts) / self.EWMA_HALFLIFE_S) < 0.05]:
            del self._rates[h]

    def pop(self, env_hash: str, exact: bool, alive,
            count_miss: bool = True) -> Optional["WorkerHandle"]:
        """Newest-first pop: exact env pool, then the fresh pool (a fresh
        worker can still apply the env). exact=True (container envs)
        never falls back — a generic process cannot retroactively enter
        the container. `alive(handle)` prunes dead entries mid-scan.
        count_miss=False for re-scans of an already-counted request."""
        for key in ((env_hash,) if exact or env_hash == ""
                    else (env_hash, "")):
            pool = self.pools.get(key)
            while pool:
                handle = pool.pop()
                if alive(handle):
                    self.hits += 1
                    return handle
        if count_miss:
            self.misses += 1
        return None


@dataclass
class _ActorWorkerWaiter:
    """One actor creation waiting for a worker. The spec rides along so
    rpc_register_worker can hand the newly registered worker its actor
    assignment IN THE REGISTRATION REPLY (no register→idle→re-offer→
    instantiate round trip)."""
    env_hash: str
    exact: bool
    fut: asyncio.Future
    spec: Optional[TaskSpec] = None
    epoch: int = 0
    pg_key: Optional[tuple] = None
    function_blob: Optional[bytes] = None


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    pid: int
    address: str = ""            # worker RPC endpoint once registered
    proc: Optional[subprocess.Popen] = None
    registered: bool = False
    # Lease state
    leased: bool = False
    lease_class: Optional[tuple] = None
    lease_resources: Dict[str, float] = field(default_factory=dict)
    lease_pg: Optional[tuple] = None     # (pg_id, bundle_index)
    is_actor_worker: bool = False
    actor_id: Optional[object] = None
    # Restart epoch of the hosted actor: create-by-actor-id dedupe keys
    # on (actor_id, epoch) so a re-driven create for the SAME epoch joins
    # this instance while a genuine restart (epoch+1) re-instantiates.
    actor_epoch: int = -1
    idle_since: float = field(default_factory=time.time)
    conn: Optional[rpc.Connection] = None
    # Runtime env this worker has applied ("" = fresh). A tagged worker is
    # dedicated: it only serves tasks with the same env hash (reference:
    # worker_pool.h dedicated workers per runtime env).
    env_hash: str = ""
    # Owner (submitter) of the current lease; OOM victim grouping key.
    lease_owner: str = ""
    # The raylet connection the lease was granted over: when it closes
    # (driver exited), the lease is reclaimed.
    lease_conn: Optional[rpc.Connection] = None
    # Launch-storm debugging: when/how the process was spawned
    # (fork | popen | container).
    spawned_at: float = 0.0
    spawn_mode: str = ""
    # The assignment dispatched in this worker's registration reply,
    # kept until its instantiate_result arrives so an idempotent
    # register_worker REPLAY re-sends the same assignment instead of
    # stranding both sides (the first reply being lost is exactly the
    # case replays exist for).
    pending_assignment: Optional[dict] = None
    # Compiled-DAG pins (dag ids): while non-empty this worker's lease
    # is load-bearing pipeline state — excluded from OOM victim
    # selection and the idle reaper until every DAG releases it.
    dag_pins: set = field(default_factory=set)


class ResourcePool:
    """Vector resource accounting: node pool + per-bundle sub-pools."""

    def __init__(self, total: Dict[str, float]):
        self.total = dict(total)
        self.available = dict(total)
        # (pg_id_bytes, bundle_index) -> {resource: amount}
        self.bundles: Dict[tuple, Dict[str, float]] = {}
        self.bundle_available: Dict[tuple, Dict[str, float]] = {}

    def fits(self, request: Dict[str, float], pg_key: Optional[tuple] = None) -> bool:
        pool = self.bundle_available.get(pg_key) if pg_key else self.available
        if pool is None:
            return False
        return all(pool.get(k, 0.0) + 1e-9 >= v for k, v in request.items() if v > 0)

    def feasible(self, request: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0.0) >= v for k, v in request.items() if v > 0)

    def acquire(self, request: Dict[str, float], pg_key: Optional[tuple] = None) -> bool:
        if not self.fits(request, pg_key):
            return False
        pool = self.bundle_available[pg_key] if pg_key else self.available
        for k, v in request.items():
            if v > 0:
                pool[k] = pool.get(k, 0.0) - v
        return True

    def release(self, request: Dict[str, float], pg_key: Optional[tuple] = None):
        if pg_key is not None:
            pool = self.bundle_available.get(pg_key)
            if pool is None:
                return
        else:
            pool = self.available
        for k, v in request.items():
            if v > 0:
                pool[k] = pool.get(k, 0.0) + v

    def reserve_bundle(self, key: tuple, resources: Dict[str, float]) -> bool:
        if key in self.bundles:
            return True
        if not self.fits(resources):
            return False
        for k, v in resources.items():
            if v > 0:
                self.available[k] = self.available.get(k, 0.0) - v
        self.bundles[key] = dict(resources)
        self.bundle_available[key] = dict(resources)
        return True

    def return_bundle(self, key: tuple):
        resources = self.bundles.pop(key, None)
        self.bundle_available.pop(key, None)
        if resources:
            for k, v in resources.items():
                if v > 0:
                    self.available[k] = self.available.get(k, 0.0) + v


class Raylet:
    def __init__(self, config: Config, gcs_address: str, session_dir: str,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 is_head: bool = False,
                 object_store_memory: Optional[int] = None,
                 node_name: str = "", slice_id: str = "", zone: str = ""):
        self.config = config
        self.gcs_address = gcs_address
        self.session_dir = session_dir
        self.node_id = NodeID.from_random()
        self.node_name = node_name or self.node_id.hex()[:8]
        self.is_head = is_head
        self.resources = resources or self._default_resources()
        self.labels = dict(labels or {})
        # TPU slice fault domain: every host of one ICI domain registers
        # the same slice_id so the GCS drains/recovers them as one gang.
        from ray_tpu.parallel.mesh import (SLICE_LABEL, ZONE_LABEL,
                                           detect_slice_id, detect_zone)
        self.slice_id = slice_id or detect_slice_id(self.labels)
        if self.slice_id:
            self.labels.setdefault(SLICE_LABEL, self.slice_id)
        # DCN locality (pod/zone): drives same-zone replacement-domain
        # preference when gangs / compiled DAGs migrate off this host.
        self.zone = zone or detect_zone(self.labels)
        if self.zone:
            self.labels.setdefault(ZONE_LABEL, self.zone)
        self.pool = ResourcePool(self.resources)
        self.server = rpc.RpcServer(f"raylet-{self.node_name}")
        self.store = ObjectStoreHost(
            object_store_memory or config.object_store_memory,
            os.path.join(session_dir, f"spill_{self.node_name}"),
        )
        self.clients = rpc.ClientPool()
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        # Env-hash-keyed warm pools (was a flat idle list).
        self._pools = WarmPools()
        # Actor creates waiting for a worker (_ActorWorkerWaiter records),
        # FIFO-served by rpc_register_worker — which dispatches the actor
        # assignment in the registration reply when the waiter carries a
        # spec.
        self._actor_worker_waiters: List[_ActorWorkerWaiter] = []
        # worker_id -> future resolved by rpc_instantiate_result (the
        # constructor outcome of a register-reply-dispatched create).
        self._instantiate_results: Dict[WorkerID, asyncio.Future] = {}
        # Counters for tests / observability (exported as deltas by the
        # metrics loop).
        self.register_reply_dispatches = 0
        self.prestart_hints_received = 0
        self._exported_pool_hits = 0
        self._exported_zero_copy_gets = 0
        self._exported_pool_misses = 0
        self._pool_gauge_envs: set = set()
        # actor:spawn/register/ctor flightrec spans, flushed to the GCS
        # task-event buffer by the heartbeat loop.
        self._pending_spans: List[dict] = []
        # Content-addressed class blobs (function_id -> pickled class),
        # prefetched ONCE per node and shipped inside the instantiate
        # payload: a 1k-actor storm costs 1 GCS KV fetch here instead of
        # 1k worker-side fetches through a saturated GCS loop.
        self._function_blobs: Dict[str, bytes] = {}
        # In-flight create_actor dedupe keyed (actor_id, num_restarts):
        # a GCS-restore re-drive (or RPC replay) for an actor whose
        # original create is STILL RUNNING here must join that create,
        # not double-instantiate the actor.
        self._creating_actors: Dict[tuple, asyncio.Future] = {}
        self._pending_leases: List[PendingLease] = []
        # Compiled-DAG lease accounting: dag_id -> worker hexes pinned on
        # this node (rpc_dag_pin_workers / rpc_dag_release_workers).
        self._dag_pins: Dict[str, set] = {}
        # Driver conns that have been granted leases: on close, their
        # leased workers are reclaimed (reference: leased workers of an
        # exited job are destroyed, worker_pool.cc DisconnectClient).
        self._lease_conns: set = set()
        self._conn_owner: Dict[Any, str] = {}   # conn -> owner address
        self._autoscaler_active = False
        # Drain protocol (planned removal): a draining raylet grants no new
        # leases, lets running work finish until the deadline, and pushes
        # its primary object copies to live peers.
        self._draining = False
        self._drain_deadline = 0.0
        self._spawned_worker_prefixes: set = set()
        self._starting_workers = 0
        self.gcs_conn: Optional[rpc.Connection] = None
        # Cluster resource view: node_id -> {available, total, address}
        self.cluster_view: Dict[NodeID, dict] = {}
        self.address = ""
        self._tasks: List[asyncio.Task] = []
        self._worker_env = dict(os.environ)
        self._stopped = False
        self._resources_dirty = False
        # Fork-server (zygote) for fast worker spawn; Popen is the fallback
        # if it is unavailable (worker_forkserver.py).
        self._workers_by_hex: Dict[str, WorkerHandle] = {}

    def _default_resources(self) -> Dict[str, float]:
        cpus = os.cpu_count() or 1
        res = {"CPU": float(cpus), "memory": 4 * 1024**3}
        res["object_store_memory"] = float(self.config.object_store_memory) \
            if hasattr(self, "config") else 2 * 1024**3
        return res

    # ------------------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self.server.register_all(self)
        actual = await self.server.start(host, port)
        self.address = f"{host}:{actual}"
        # Register with GCS and subscribe to cluster events.
        self.gcs_conn = await rpc.connect(self.gcs_address, self._on_gcs_push)
        await self._register_with_gcs()
        self._tasks.append(asyncio.ensure_future(self._heartbeat_loop()))
        self._tasks.append(asyncio.ensure_future(self._idle_worker_reaper()))
        self._tasks.append(asyncio.ensure_future(self._start_forkserver()))
        self._tasks.append(asyncio.ensure_future(self._report_metrics_loop()))
        from ray_tpu.util import metrics as _metrics
        self._tasks.append(_metrics.start_loop_lag_probe("raylet"))
        # Worker stdout/stderr -> GCS "logs" pubsub -> driver echo
        # (reference: log_monitor.py LogMonitor).
        from ray_tpu._private.log_monitor import LogMonitor

        async def _publish_logs(message):
            await self.gcs_conn.request(
                "publish", {"channel": "logs", "message": message})

        def _pid_of(worker_hex12: str) -> int:
            for full, h in self._workers_by_hex.items():
                if full.startswith(worker_hex12):
                    return h.pid
            return -1

        self.log_monitor = LogMonitor(
            self.session_dir, self.node_name, _publish_logs, pid_of=_pid_of,
            owns=lambda h: h in self._spawned_worker_prefixes)
        self.log_monitor.start()
        # OOM defense (reference: memory_monitor.h + worker killing
        # policies): above the threshold, kill the newest leased worker of
        # the owner with the most leases.
        from ray_tpu._private.memory_monitor import MemoryMonitor
        if self.config.memory_monitor_interval_s > 0:
            self.memory_monitor = MemoryMonitor(
                self.config.memory_usage_threshold,
                self.config.memory_monitor_interval_s,
                self._on_memory_pressure)
            self.memory_monitor.start()
        logger.info("raylet %s started at %s", self.node_name, self.address)
        return self.address

    def _on_memory_pressure(self, usage: float):
        from ray_tpu._private.memory_monitor import pick_victim
        victim = pick_victim(list(self.workers.values()))
        if victim is None:
            return
        logger.warning(
            "node memory usage %.0f%% above threshold; OOM-killing worker "
            "pid=%s (owner %s) — the task will retry per its budget "
            "(reference: task_oom_retries)", usage * 100, victim.pid,
            victim.lease_owner)
        try:
            if victim.pid > 0:
                os.kill(victim.pid, 9)
        except OSError:
            pass

    async def stop(self):
        self._stopped = True
        from ray_tpu.util import metrics as _metrics
        _metrics.release_reporter(self)
        for gname in ("ray_tpu_raylet_pending_leases",
                      "ray_tpu_raylet_idle_workers",
                      "ray_tpu_raylet_leased_workers",
                      "ray_tpu_raylet_dag_pinned_workers",
                      "ray_tpu_worker_pool_hits_total",
                      "ray_tpu_worker_pool_misses_total"):
            _metrics.remove(gname, {"Node": self.node_name})
        for env_hash in self._pool_gauge_envs:
            _metrics.remove("ray_tpu_worker_pool_size",
                            {"Node": self.node_name,
                             "Env": env_hash or "fresh"})
        if getattr(self, "log_monitor", None) is not None:
            self.log_monitor.stop()
        if getattr(self, "memory_monitor", None) is not None:
            self.memory_monitor.stop()
        for t in self._tasks:
            t.cancel()
        for w in self.workers.values():
            if w.proc is not None:
                try:
                    w.proc.terminate()
                except Exception:
                    pass
            elif w.pid > 0:
                try:
                    os.kill(w.pid, 15)
                except OSError:
                    pass
        for w in self.workers.values():
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=2)
                except Exception:
                    try:
                        w.proc.kill()
                    except Exception:
                        pass
        await self.server.stop()
        await self.clients.close_all()
        self.store.destroy()

    async def _register_with_gcs(self):
        info = NodeInfo(
            node_id=self.node_id, address=self.address,
            resources_total=dict(self.pool.total),
            resources_available=dict(self.pool.available),
            labels=self.labels, is_head=self.is_head,
            slice_id=self.slice_id, zone=self.zone,
        )
        reply = await self.gcs_conn.request("register_node", {
            "node_info": info,
            # Actor-liveness reconcile on (re)registration: a restarted
            # GCS restored from a snapshot may believe actors are ALIVE
            # on workers that died during its outage (their one-shot
            # death reports were lost) — the live set lets it drive
            # those through the failure path immediately.
            "live_worker_ids": [h.worker_id for h in self.workers.values()
                                if h.pid > 0],
        })
        for node_id, view in reply.get("cluster_view", {}).items():
            if node_id != self.node_id:
                self.cluster_view[node_id] = view
        await self.gcs_conn.request(
            "subscribe", {"channels": ["resources", "nodes", "actors"]})

    async def _report_metrics_loop(self):
        """Node-side flight-recorder gauges (worker pool + lease queue
        depth) plus the registry push for processes where the raylet is
        the only daemon (`ray_tpu start` worker nodes). When the GCS or a
        driver core shares this process, the per-process reporter claim
        leaves the push to whoever claimed first — the gauges still
        update in the shared registry either way."""
        from ray_tpu.util import metrics as _metrics
        agent = _metrics.MetricsAgent(f"raylet:{self.node_name}",
                                      self.gcs_conn.request)
        while not self._stopped:
            await asyncio.sleep(self.config.metrics_report_interval_s)
            tags = {"Node": self.node_name}

            def g(name, desc):
                return _metrics.Gauge(name, desc, tag_keys=("Node",))

            g("ray_tpu_raylet_pending_leases",
              "lease requests queued at the raylet").set(
                float(len(self._pending_leases)), tags=tags)
            g("ray_tpu_raylet_idle_workers",
              "registered workers idle in the pool").set(
                float(len(self._pools)), tags=tags)
            g("ray_tpu_raylet_leased_workers",
              "workers currently leased out").set(
                float(sum(1 for w in self.workers.values() if w.leased)),
                tags=tags)
            g("ray_tpu_raylet_dag_pinned_workers",
              "workers whose lease a compiled DAG holds pinned").set(
                float(sum(1 for w in self.workers.values()
                          if w.dag_pins)), tags=tags)
            # Object-plane health: occupancy/pinned/spill gauges plus the
            # zero-copy get counter (delta-exported like pool hits).
            st = self.store.stats()
            g("ray_tpu_store_occupancy_bytes",
              "bytes allocated to objects in the shm segment pool").set(
                float(st["used"]), tags=tags)
            g("ray_tpu_store_pinned_bytes",
              "bytes pinned by outstanding zero-copy views").set(
                float(st["pinned_bytes"]), tags=tags)
            g("ray_tpu_store_spilled_bytes",
              "cumulative bytes spilled to external storage").set(
                float(st["bytes_spilled"]), tags=tags)
            lookups = st["num_hits"] + st["num_misses"]
            g("ray_tpu_store_hit_ratio",
              "fraction of store lookups served from shm").set(
                (st["num_hits"] / lookups) if lookups else 1.0, tags=tags)
            zc = st["num_zero_copy_gets"]
            if zc > self._exported_zero_copy_gets:
                _metrics.Counter(
                    "ray_tpu_store_zero_copy_gets_total",
                    "same-node gets served as pinned zero-copy shm views",
                    tag_keys=("Node",)).inc(
                    zc - self._exported_zero_copy_gets, tags=tags)
                self._exported_zero_copy_gets = zc
            # Warm-pool health: per-env pool depth + cumulative hit/miss.
            # Rows for envs whose pool emptied AND whose floor expired
            # are removed (not left at 0 forever): a long-lived node
            # serving many per-job env hashes must not grow metric
            # cardinality without bound.
            sizes = self._pools.sizes()
            for env_hash in set(self._pool_gauge_envs) | set(sizes):
                depth = sizes.get(env_hash, 0)
                if (depth == 0 and env_hash not in sizes
                        and self._pools.floor(env_hash) == 0):
                    _metrics.remove("ray_tpu_worker_pool_size",
                                    {"Node": self.node_name,
                                     "Env": env_hash or "fresh"})
                    self._pool_gauge_envs.discard(env_hash)
                    continue
                self._pool_gauge_envs.add(env_hash)
                _metrics.Gauge(
                    "ray_tpu_worker_pool_size",
                    "idle workers per runtime-env warm pool",
                    tag_keys=("Node", "Env")).set(
                    float(depth),
                    tags={"Node": self.node_name,
                          "Env": env_hash or "fresh"})
            hits, misses = self._pools.hits, self._pools.misses
            if hits > self._exported_pool_hits:
                _metrics.Counter(
                    "ray_tpu_worker_pool_hits_total",
                    "worker requests served from a warm pool",
                    tag_keys=("Node",)).inc(
                    hits - self._exported_pool_hits, tags=tags)
                self._exported_pool_hits = hits
            if misses > self._exported_pool_misses:
                _metrics.Counter(
                    "ray_tpu_worker_pool_misses_total",
                    "worker requests that found no warm worker (cold "
                    "spawn or wait)", tag_keys=("Node",)).inc(
                    misses - self._exported_pool_misses, tags=tags)
                self._exported_pool_misses = misses
            if not self.config.metrics_agent_enabled:
                continue
            if not _metrics.claim_reporter(self):
                continue
            rpc.export_transport_metrics()
            snap = _metrics.snapshot()
            if not snap:
                continue
            try:
                await agent.ship(snap)
            except rpc.RpcError:
                pass

    async def _heartbeat_loop(self):
        while not self._stopped:
            await asyncio.sleep(self.config.heartbeat_interval_s)
            try:
                reply = await self.gcs_conn.request("heartbeat", {
                    "node_id": self.node_id,
                    "resources_available": dict(self.pool.available),
                    # Queued lease shapes feed the autoscaler's demand
                    # bin-packing (reference: resource_demand_scheduler.py).
                    "pending_demand": self._pending_demand_shapes(64),
                    # Warm-pool depth per env: the GCS creation pipeline
                    # routes storms toward live warm capacity.
                    "idle_workers": self._pools.sizes(),
                })
                if reply.get("reregister"):
                    # GCS restarted without our node in its (restored) table.
                    await self._register_with_gcs()
                if reply.get("report_actors"):
                    # Post-restore handshake: tell the (restarted) GCS
                    # which workers actually live here so it can restart
                    # ALIVE actors whose death reports it never received.
                    await self.gcs_conn.request("reconcile_actors", {
                        "node_id": self.node_id,
                        "live_worker_ids": [
                            h.worker_id for h in self.workers.values()
                            if h.pid > 0],
                    })
                self._autoscaler_active = bool(
                    reply.get("autoscaler_active"))
                self._check_worker_deaths()
                await self._flush_spans()
                if self._resources_dirty:
                    self._resources_dirty = False
                    await self._report_resources()
            except rpc.RpcError:
                # Head fault tolerance: keep dialing until the GCS (or its
                # restarted replacement on the same address) answers.
                logger.warning("raylet %s lost GCS connection; reconnecting",
                               self.node_name)
                await self._reconnect_gcs()

    def _pending_demand_shapes(self, cap: int) -> list:
        """Queued lease demand for the autoscaler, one shape per needed
        GRANT (a multi-grant request with count=n is n workers of demand)."""
        shapes: list = []
        for req in self._pending_leases:
            if req.fut.done():
                continue
            for _ in range(min(req.count, cap - len(shapes))):
                shapes.append(dict(req.spec.resources))
            if len(shapes) >= cap:
                break
        return shapes

    def _record_span(self, trace_id: str, name: str, start: float,
                     end: float):
        """Launch-path flight-recorder span (actor:spawn / actor:register
        / actor:ctor): buffered here, flushed to the GCS task-event ring
        by the heartbeat loop so `ray_tpu timeline` shows where a slow
        actor launch spent its time."""
        if not self.config.task_events_enabled:
            return
        self._pending_spans.append({
            "kind": "span", "trace_id": trace_id,
            "span_id": os.urandom(8).hex(), "parent_id": "",
            "name": name, "task_id": trace_id,
            "start": start, "end": end})

    async def _flush_spans(self):
        if not self._pending_spans:
            return
        spans, self._pending_spans = self._pending_spans, []
        try:
            await self.gcs_conn.request("report_task_events",
                                        {"events": spans})
        except rpc.RpcError:
            pass

    async def _reconnect_gcs(self):
        while not self._stopped:
            try:
                self.gcs_conn = await rpc.connect(self.gcs_address,
                                                  self._on_gcs_push)
                await self._register_with_gcs()
                logger.info("raylet %s re-registered with GCS",
                            self.node_name)
                return
            except Exception:
                await asyncio.sleep(
                    min(1.0, self.config.heartbeat_interval_s))

    async def _report_resources(self):
        try:
            await self.gcs_conn.request("report_resources", {
                "node_id": self.node_id,
                "available": dict(self.pool.available),
            })
        except rpc.RpcError:
            pass

    def _mark_resources_dirty(self):
        """Push the new resource view to the GCS now (coalesced), so
        available_resources() reads don't race the heartbeat period."""
        if self._resources_dirty:
            return
        self._resources_dirty = True

        async def _flush():
            await asyncio.sleep(0)  # coalesce a burst of acquire/release
            if self._resources_dirty and not self._stopped:
                self._resources_dirty = False
                await self._report_resources()

        asyncio.ensure_future(_flush())

    def _on_gcs_push(self, method: str, payload):
        if method != "pub":
            return
        channel = payload["channel"]
        msg = payload["message"]
        if channel == "resources":
            if msg.get("draining"):
                # A draining peer must stop being a spillback/migration
                # target.
                self.cluster_view.pop(msg["node_id"], None)
            elif msg["node_id"] != self.node_id:
                self.cluster_view[msg["node_id"]] = {
                    "available": msg["available"], "total": msg["total"],
                    "address": msg.get("address", ""),
                    "labels": msg.get("labels", {})}
                # A peer freeing resources may unblock queued lease
                # requests via spillback.
                self._try_dispatch()
        elif channel == "nodes":
            if msg["event"] in ("dead", "draining"):
                self.cluster_view.pop(msg.get("node_id"), None)

    # ------------------------------------------------------------------
    # Worker pool

    def _worker_env_for(self, worker_id: WorkerID) -> Dict[str, str]:
        env = dict(self._worker_env)
        # Workers must import ray_tpu regardless of the driver's cwd/sys.path.
        import ray_tpu
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
            ray_tpu.__file__)))
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (pkg_root + os.pathsep + existing).rstrip(os.pathsep)
        env["RAY_TPU_RAYLET_ADDRESS"] = self.address
        env["RAY_TPU_GCS_ADDRESS"] = self.gcs_address
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        return env

    def _worker_log_path(self, worker_id: WorkerID) -> str:
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        return os.path.join(log_dir, f"worker-{worker_id.hex()[:12]}.log")

    async def _start_forkserver(self):
        """Bring up (or join) the process-wide zygote and prestart workers."""
        fs = _SharedForkServer.get()
        await fs.ensure_started(self._worker_env_for(WorkerID.from_random()))
        if not fs.dead and not self._stopped:
            fs.on_ready(self._prestart_workers)

    def _on_forkserver_event(self, event: str, msg: dict):
        if event == "spawned":
            if self._stopped:
                # Forked after our stop(): nothing will ever lease it.
                try:
                    os.kill(msg["pid"], 15)
                except OSError:
                    pass
                return
            handle = self._workers_by_hex.get(msg.get("worker_id"))
            if handle is not None:
                handle.pid = msg["pid"]
            return
        if self._stopped:
            return
        # exit
        handle = self._workers_by_hex.pop(msg.get("worker_id"), None)
        if handle is not None and handle.worker_id in self.workers:
            if handle.registered and handle.conn is not None \
                    and not handle.conn.closed:
                handle.conn.abort(rpc.ConnectionLost("process exited"))
            else:
                asyncio.ensure_future(
                    self._on_worker_disconnect(handle.worker_id))

    def _spawn_worker(self, container_env: Optional[dict] = None
                      ) -> WorkerHandle:
        return self._spawn_workers(1, container_env)[0]

    def _spawn_workers(self, n: int,
                       container_env: Optional[dict] = None
                       ) -> List[WorkerHandle]:
        """Start `n` workers. Generic workers ride ONE multi-spawn
        request through the zygote (one pipe write forks N children);
        container workers stay per-process (each is its own podman/docker
        invocation)."""
        if n <= 0:
            return []
        if container_env is not None:
            return [self._spawn_container_worker(container_env)
                    for _ in range(n)]
        jobs: List[tuple] = []
        for _ in range(n):
            worker_id = WorkerID.from_random()
            env = self._worker_env_for(worker_id)
            log_path = self._worker_log_path(worker_id)
            self._spawned_worker_prefixes.add(worker_id.hex()[:12])
            jobs.append((worker_id, env, log_path))
        fs = _SharedForkServer.get()
        # Fast path: ask the zygote to fork the workers (~ms each, vs
        # seconds for a cold python+jax start). Requests written before
        # the zygote finishes importing are buffered. The FULL worker env
        # ships with each request (the child resets os.environ to it) —
        # the zygote is a long-lived singleton whose template env can be
        # stale.
        if fs.spawn_many([(env, lp) for _wid, env, lp in jobs], self):
            handles = []
            now = time.time()
            for worker_id, _env, _lp in jobs:
                handle = WorkerHandle(worker_id=worker_id, pid=-1,
                                      proc=None)
                handle.spawn_mode = "fork"
                handle.spawned_at = now
                self.workers[worker_id] = handle
                self._workers_by_hex[worker_id.hex()] = handle
                self._starting_workers += 1
                handles.append(handle)
            return handles
        return [self._popen_spawn(worker_id, env, lp)
                for worker_id, env, lp in jobs]

    @staticmethod
    def _start_worker_proc(env: Dict[str, str],
                           log_path: str) -> subprocess.Popen:
        """The one place a generic worker process is exec'd (normal
        Popen path AND zygote-death failover)."""
        # ray-tpu: noqa(ASYNC-BLOCK): cold-path spawn fallback; one append-mode open of the worker log (forkserver covers the hot path)
        out = open(log_path, "ab")
        return subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_main"],
            env=env, stdout=out, stderr=subprocess.STDOUT,
            start_new_session=True,
        )

    def _popen_spawn(self, worker_id: WorkerID, env: Dict[str, str],
                     log_path: str) -> WorkerHandle:
        proc = self._start_worker_proc(env, log_path)
        handle = WorkerHandle(worker_id=worker_id, pid=proc.pid, proc=proc)
        handle.spawn_mode = "popen"
        handle.spawned_at = time.time()
        self.workers[worker_id] = handle
        self._workers_by_hex[worker_id.hex()] = handle
        self._starting_workers += 1
        return handle

    def _spawn_container_worker(self, container_env: dict) -> WorkerHandle:
        # Containerized worker (runtime_env={"container": ...}): start
        # the worker inside the image via podman/docker (or the test
        # hook), pre-dedicated to this env's hash so only matching
        # leases ever use it (reference: runtime_env/container.py).
        worker_id = WorkerID.from_random()
        env = self._worker_env_for(worker_id)
        log_path = self._worker_log_path(worker_id)
        self._spawned_worker_prefixes.add(worker_id.hex()[:12])
        from ray_tpu._private import runtime_env_container as rec
        from ray_tpu._private.runtime_env import env_hash as _ehash
        argv = rec.build_worker_command(
            container_env["container"], env=env,
            session_dir=self.session_dir)
        # ray-tpu: noqa(ASYNC-BLOCK): container spawn is explicitly a slow path (podman/docker exec); one log-file open alongside
        out = open(log_path, "ab")
        proc = subprocess.Popen(argv, env=env, stdout=out,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
        handle = WorkerHandle(worker_id=worker_id, pid=proc.pid,
                              proc=proc)
        handle.env_hash = (container_env.get("_hash")
                           or _ehash(container_env))
        handle.spawn_mode = "container"
        handle.spawned_at = time.time()
        self.workers[worker_id] = handle
        self._workers_by_hex[worker_id.hex()] = handle
        self._starting_workers += 1
        return handle

    def _popen_failover_batch(self, jobs: List[tuple]):
        """Spawns that were buffered at a zygote that died before forking
        them: start each via Popen, reusing the handle already tracked
        for the spawn (supply accounting and any actor-create waiter keep
        working; only the warm fork is lost)."""
        for env, log_path in jobs:
            handle = self._workers_by_hex.get(
                env.get("RAY_TPU_WORKER_ID", ""))
            if (handle is None or handle.registered or handle.proc
                    is not None or self._stopped):
                continue
            try:
                proc = self._start_worker_proc(env, log_path)
            except Exception:
                asyncio.ensure_future(
                    self._on_worker_disconnect(handle.worker_id))
                continue
            handle.proc = proc
            handle.pid = proc.pid
            handle.spawn_mode = "popen"

    @rpc.idempotent
    async def rpc_register_worker(self, conn, payload):
        """Called by a worker process once its RPC server is up.

        The reply can carry the worker's FIRST assignment: when an actor
        creation is already waiting for a worker of this env, the lease
        happens here and the instantiate payload rides the registration
        reply — the worker starts constructing immediately instead of
        going idle, being re-offered, and waiting for a separate
        instantiate dial (the register→idle→re-offer→dispatch round trip
        a launch storm pays per actor)."""
        worker_id = payload["worker_id"]
        handle = self.workers.get(worker_id)
        if handle is None:
            handle = WorkerHandle(worker_id=worker_id, pid=payload["pid"])
            self.workers[worker_id] = handle
        handle.address = payload["address"]
        handle.registered = True
        handle.conn = conn
        handle.idle_since = time.time()
        self._starting_workers = max(0, self._starting_workers - 1)
        # (Boot latency itself is visible through the Mode=cold rows of
        # ray_tpu_worker_spawn_seconds, observed ONCE per actor create
        # in _create_actor — observing it here too double-counted every
        # cold create and emitted rows for prestarts nobody waited on.)
        conn.peer_info["worker_id"] = worker_id
        prev = conn.on_close
        def _on_close(c, _prev=prev):
            asyncio.ensure_future(self._on_worker_disconnect(worker_id))
            if _prev:
                _prev(c)
        conn.on_close = _on_close
        reply = {"node_id": self.node_id, "config": self.config.to_dict()}
        if not handle.leased:
            assignment = self._try_register_assignment(handle)
            if assignment is not None:
                handle.pending_assignment = assignment
                reply["assignment"] = assignment
            else:
                self._offer_idle_worker(handle)
        elif handle.pending_assignment is not None:
            # Replayed registration whose original reply (carrying the
            # assignment) may have been lost: re-send the SAME
            # assignment. The worker applies it once; the create's
            # result future is still waiting on instantiate_result.
            reply["assignment"] = handle.pending_assignment
        self._try_dispatch()
        return reply

    def _try_register_assignment(self, handle: WorkerHandle
                                 ) -> Optional[dict]:
        """Serve the oldest compatible actor-create waiter by leasing the
        registering worker NOW and returning the instantiate payload for
        the registration reply. The waiter's future resolves to the
        result future rpc_instantiate_result will complete."""
        for waiter in list(self._actor_worker_waiters):
            if waiter.fut.done():
                self._actor_worker_waiters.remove(waiter)
                continue
            if waiter.spec is None:
                continue
            if not (handle.env_hash == waiter.env_hash
                    or (handle.env_hash == "" and not waiter.exact)):
                continue
            self._actor_worker_waiters.remove(waiter)
            self._lease_worker_for_actor(handle, waiter.spec,
                                         waiter.pg_key)
            result_fut = asyncio.get_event_loop().create_future()
            self._instantiate_results[handle.worker_id] = result_fut
            self.register_reply_dispatches += 1
            waiter.fut.set_result(("dispatched", handle, result_fut))
            assignment = {"spec": waiter.spec,
                          "num_restarts": waiter.epoch}
            if waiter.function_blob is not None:
                assignment["function_blob"] = waiter.function_blob
            return assignment
        return None

    async def _prefetch_function(self, function_id: str
                                 ) -> Optional[bytes]:
        """Fetch (once per node) the content-addressed class blob so the
        instantiate payload can carry it — the id is a content hash, so
        the cache never goes stale. Best-effort: None just means the
        worker falls back to its own KV fetch."""
        blob = self._function_blobs.get(function_id)
        if blob is not None:
            return blob
        try:
            blob = await self.gcs_conn.request("kv_get", {
                "namespace": "funcs", "key": function_id.encode()})
        except Exception:  # noqa: BLE001 — prefetch is an optimization
            return None
        if blob is None:
            return None
        if len(self._function_blobs) >= 128:
            self._function_blobs.pop(next(iter(self._function_blobs)))
        self._function_blobs[function_id] = blob
        return blob

    def _lease_worker_for_actor(self, worker: WorkerHandle, spec: TaskSpec,
                                pg_key: Optional[tuple]):
        """Stamp the lease fields for an actor create (resources were
        acquired by _create_actor before the spawn)."""
        worker.leased = True
        worker.lease_owner = spec.owner_address
        if spec.env_hash():
            worker.env_hash = spec.env_hash()
        worker.is_actor_worker = True
        worker.actor_id = spec.actor_id
        worker.lease_resources = dict(spec.resources)
        worker.lease_pg = pg_key
        self._mark_resources_dirty()

    @rpc.idempotent
    async def rpc_instantiate_result(self, conn, payload):
        """Constructor outcome of a register-reply-dispatched create,
        reported by the worker over its raylet connection."""
        handle = self.workers.get(payload["worker_id"])
        if handle is not None:
            handle.pending_assignment = None
        fut = self._instantiate_results.pop(payload["worker_id"], None)
        if fut is not None and not fut.done():
            result = payload.get("result")
            if isinstance(result, dict) and "_infra_error" in result:
                # The worker's dispatch plumbing (not the constructor)
                # failed: re-raise into the create path so the GCS
                # retries, exactly like the old request/reply dispatch.
                fut.set_exception(RuntimeError(result["_infra_error"]))
            else:
                fut.set_result(result)
        return True

    # ---- compiled-DAG lease pinning -----------------------------------

    @rpc.idempotent
    async def rpc_dag_pin_workers(self, conn, payload):
        """Pin the leases of the workers hosting `actor_ids` for a
        compiled DAG's lifetime: pinned workers are excluded from OOM
        victim selection and the idle reaper, and stay visible in
        rpc_dag_lease_accounting until rpc_dag_release_workers (or
        worker death) drops them. Set-based, so replays are no-ops."""
        dag_id = payload["dag_id"]
        by_actor = {h.actor_id: h for h in self.workers.values()
                    if h.is_actor_worker and h.actor_id is not None}
        # Validate-then-pin (atomic per raylet): a missing actor midway
        # through the loop must not leave the earlier ones half-pinned.
        handles = []
        for actor_id in payload["actor_ids"]:
            handle = by_actor.get(actor_id)
            if handle is None:
                raise rpc.RpcError(
                    f"no live worker hosts actor {actor_id.hex()[:12]} "
                    f"on node {self.node_name}")
            handles.append((actor_id, handle))
        pinned = {}
        for actor_id, handle in handles:
            handle.dag_pins.add(dag_id)
            self._dag_pins.setdefault(dag_id, set()).add(
                handle.worker_id.hex())
            pinned[actor_id.hex()] = handle.worker_id.hex()
        return pinned

    @rpc.idempotent
    async def rpc_dag_release_workers(self, conn, payload):
        """Release every lease `dag_id` pinned on this node. (Recovery's
        partial release is per-RAYLET — a dead participant's pin is
        already dropped by _on_worker_disconnect, and a migrating DAG
        releases whole draining raylets — so no worker-level subset is
        needed here.)"""
        dag_id = payload["dag_id"]
        released = sorted(self._dag_pins.pop(dag_id, set()))
        for handle in self.workers.values():
            handle.dag_pins.discard(dag_id)
        return released

    @rpc.idempotent
    async def rpc_dag_lease_accounting(self, conn, payload):
        """{dag_id: [worker hexes]} of live pinned leases on this node."""
        return {dag_id: sorted(ws)
                for dag_id, ws in self._dag_pins.items() if ws}

    async def _on_worker_disconnect(self, worker_id: WorkerID):
        handle = self.workers.pop(worker_id, None)
        self._workers_by_hex.pop(worker_id.hex(), None)
        fut = self._instantiate_results.pop(worker_id, None)
        if fut is not None and not fut.done():
            fut.set_exception(RuntimeError(
                "worker died during actor construction"))
        if handle is None:
            return
        if handle.dag_pins:
            # The DAG's failure watcher surfaces the death to the driver;
            # here the lease accounting must not leak a dead worker.
            whex = handle.worker_id.hex()
            for dag_id in list(handle.dag_pins):
                pins = self._dag_pins.get(dag_id)
                if pins is not None:
                    pins.discard(whex)
                    if not pins:
                        self._dag_pins.pop(dag_id, None)
            handle.dag_pins.clear()
        if not handle.registered:
            # Died during startup: it still counts against supply.
            self._starting_workers = max(0, self._starting_workers - 1)
        self._pools.remove(handle)
        if handle.leased:
            # Clear the flag with the release: the create path that our
            # instantiate-future exception wakes runs
            # _unlease_failed_create, which must not release AGAIN (an
            # unclamped double release makes available exceed total and
            # the node over-schedules forever).
            handle.leased = False
            self.pool.release(handle.lease_resources, handle.lease_pg)
            self._mark_resources_dirty()
        if handle.is_actor_worker and handle.actor_id is not None:
            try:
                await self.gcs_conn.request("report_actor_failure", {
                    "actor_id": handle.actor_id,
                    # The dying worker's id lets the GCS drop stale reports
                    # about an instance it already replaced (migration can
                    # recreate the actor faster than the old process exit
                    # is detected).
                    "worker_id": handle.worker_id,
                    "reason": f"worker process {handle.pid} died"})
            except rpc.RpcError:
                pass
        self._try_dispatch()

    def _check_worker_deaths(self):
        for worker_id, handle in list(self.workers.items()):
            if handle.proc is not None and handle.proc.poll() is not None:
                if handle.registered and handle.conn is not None \
                        and not handle.conn.closed:
                    handle.conn.abort(rpc.ConnectionLost("process exited"))
                else:
                    asyncio.ensure_future(self._on_worker_disconnect(worker_id))

    async def _idle_worker_reaper(self):
        """Kill surplus idle workers beyond each pool's floor.

        Per-env floors (not one global count): the fresh pool keeps the
        node's prestart floor, and every env pool keeps its demand/hint
        floor — the reaper can no longer eat a warm pool another env just
        paid to populate (the old single global floor did exactly that:
        any env's idles counted against the one shared number)."""
        while True:
            await asyncio.sleep(5.0)
            self._pools.prune()
            fresh_floor = max(2, int(self.pool.total.get("CPU", 1)))
            for env_hash, pool in list(self._pools.pools.items()):
                floor = self._pools.floor(env_hash, fresh_floor)
                surplus = len(pool) - floor
                if surplus <= 0:
                    continue
                # DAG-pinned workers are load-bearing pipeline state even
                # if they ever land back in a pool: never reap them.
                for handle in [h for h in list(pool)
                               if not h.dag_pins][:surplus]:
                    pool.remove(handle)
                    try:
                        if handle.conn:
                            await handle.conn.push("shutdown", {})
                    except Exception:
                        pass

    def _offer_idle_worker(self, handle: "WorkerHandle"):
        """A worker became available: serve the oldest compatible waiting
        actor-create (FIFO — see rpc_create_actor) or return it to its
        env's warm pool. Every idle-return path goes through here so a
        freed worker can rescue a waiting create whose own spawn died."""
        for waiter in list(self._actor_worker_waiters):
            if waiter.fut.done():
                self._actor_worker_waiters.remove(waiter)
                continue
            if handle.env_hash == waiter.env_hash or \
                    (handle.env_hash == "" and not waiter.exact):
                self._actor_worker_waiters.remove(waiter)
                waiter.fut.set_result(("worker", handle, None))
                return
        self._pools.put(handle)

    def _get_idle_worker(self, env_hash: str = "", exact: bool = False,
                         record: bool = True,
                         demand_n: int = 1) -> Optional[WorkerHandle]:
        """Pop a live idle worker compatible with `env_hash`: exact-match
        tagged workers preferred, fresh ("") workers serve any env.
        exact=True (container envs) never falls back to a fresh worker —
        a generic process cannot retroactively enter the container.

        record=False for RE-scans of a request that was already counted
        (dispatch-loop passes over a queued lease, a create's last-chance
        retry): counting each pass would inflate the EWMA demand floor
        and the miss counter with phantom requests. demand_n: workers of
        demand this request represents (a count=N multi-grant lease is N,
        not 1 — undersizing the EWMA floor ~Nx starves warm pools for
        multi-worker workloads)."""
        if record:
            self._pools.note_demand(env_hash, demand_n)
        return self._pools.pop(
            env_hash, exact,
            lambda h: (h.registered and h.worker_id in self.workers
                       and not (h.conn and h.conn.closed)),
            count_miss=record)

    @staticmethod
    def _container_env(spec) -> Optional[dict]:
        env = getattr(spec, "runtime_env", None) or {}
        return env if env.get("container") else None

    def _ensure_worker_supply(self):
        if self._draining:
            return
        # Count only leases the pool could actually serve concurrently:
        # spawning workers for requests that can't get resources just burns
        # CPU on process startup (round-1 regression on small boxes).
        avail = dict(self.pool.available)
        free_hashes = self._pools.hash_list()
        demand = 0
        container_demand: list = []
        # Container workers still starting (spawned, not yet registered):
        # their env hash is pre-set at spawn.
        starting_hashes = [h.env_hash for h in self.workers.values()
                           if not h.registered and h.env_hash]
        n_starting_container = len(starting_hashes)
        for req in self._pending_leases:
            if req.fut.done():
                continue
            spec = req.spec
            # A multi-grant request is `count` workers of demand, each
            # gated on the resources its grant would consume.
            for _ in range(req.count):
                if not all(avail.get(k, 0) >= v
                           for k, v in spec.resources.items() if v > 0):
                    break
                for k, v in spec.resources.items():
                    avail[k] = avail.get(k, 0) - v
                eh = req.env_hash
                cenv = req.container_env
                if cenv is not None:
                    # Containerized lease: only an exact-hash worker (idle
                    # or already starting) can serve it.
                    if eh in free_hashes:
                        free_hashes.remove(eh)
                    elif eh in starting_hashes:
                        starting_hashes.remove(eh)
                    else:
                        container_demand.append(cenv)
                    continue
                if eh in free_hashes:
                    free_hashes.remove(eh)
                elif "" in free_hashes:
                    free_hashes.remove("")
                else:
                    demand += 1
        spawned_container = 0
        for cenv in container_demand:
            if self.config.max_workers_per_node - len(self.workers) <= 0:
                break
            try:
                self._spawn_worker(container_env=cenv)
                spawned_container += 1
            except Exception:
                logger.exception("containerized worker spawn failed")
                break
        # Container spawns count in _starting_workers but serve only their
        # own env hash — exclude them from the generic supply.
        supply = max(0, self._starting_workers - n_starting_container
                     - spawned_container)
        can_start = self.config.max_workers_per_node - len(self.workers)
        if demand > supply and can_start <= 0:
            # The worker cap is consumed but pending leases can't use what's
            # idle: evict env-dedicated idle workers (oldest first) to make
            # room — otherwise distinct runtime envs permanently pin worker
            # slots and scheduling deadlocks (reference: worker_pool.cc
            # kills idle dedicated workers under pressure).
            tagged = [h for pool_hash, pool in self._pools.pools.items()
                      if pool_hash != "" for h in pool]
            for handle in sorted(tagged,
                                 key=lambda h: h.idle_since
                                 )[:demand - supply]:
                self._pools.remove(handle)
                self.workers.pop(handle.worker_id, None)
                self._workers_by_hex.pop(handle.worker_id.hex(), None)
                if handle.conn:
                    asyncio.ensure_future(self._push_shutdown(handle))
                can_start += 1
        self._spawn_workers(min(max(0, demand - supply), max(0, can_start)))

    async def _push_shutdown(self, handle: WorkerHandle):
        try:
            await handle.conn.push("shutdown", {})
        except Exception:
            pass

    # ------------------------------------------------------------------
    # Drain protocol (planned removal)

    @rpc.idempotent
    async def rpc_drain(self, conn, payload):
        """GCS -> raylet drain notice: stop granting leases, finish running
        work up to the deadline, push primary object copies to live peers,
        and report drain_complete once idle.

        `gang_addresses` lists fellow hosts of this node's slice draining
        in the same gang: they are pruned from the cluster view up front
        (gang-coherent rejection) so neither a lease spillback nor an
        object push-off can route work INTO the dying slice before the
        per-peer pubsub notices land."""
        gang = set(payload.get("gang_addresses") or [])
        if gang:
            for nid, view in list(self.cluster_view.items()):
                if view.get("address") in gang:
                    self.cluster_view.pop(nid, None)
        if self._draining:
            return True
        self._draining = True
        self._drain_deadline = time.time() + float(
            payload.get("deadline_s", 30.0))
        logger.info("raylet %s draining (deadline in %.1fs)",
                    self.node_name, self._drain_deadline - time.time())
        # Bounce queued lease requests: the submitter re-requests and the
        # draining guard spills it to a live peer.
        for req in self._pending_leases:
            if not req.fut.done():
                req.fut.set_result({"retry": True})
        self._pending_leases.clear()
        self._tasks.append(asyncio.ensure_future(self._drain_to_idle()))
        return True

    def _drain_spill_target(self, resources: Dict[str, float]):
        """Best live peer for a lease rejected by the drain: available
        capacity preferred, feasible-by-totals accepted."""
        fallback = None
        for _nid, view in self.cluster_view.items():
            if not view.get("address"):
                continue
            if all(view.get("available", {}).get(k, 0) >= v
                   for k, v in resources.items() if v > 0):
                return view["address"]
            if fallback is None and all(
                    view.get("total", {}).get(k, 0) >= v
                    for k, v in resources.items() if v > 0):
                fallback = view["address"]
        return fallback

    async def _drain_to_idle(self):
        """Background drain worker: migrate objects, wait for running work,
        then tell the GCS this node is safe to kill.

        Compiled-DAG pins are counted EXPLICITLY: pinned workers are
        excluded from the idle reaper, so without intervention a DAG
        whose driver never migrates would hold its leases to the bitter
        end and wedge drain_complete at the deadline. A migrating DAG
        releases its pins itself (dag_release hand-off on the drain
        notice); whatever pins remain once every ordinary lease has
        drained are SHED near the deadline — the pinned workers are shut
        down (they would die at the deadline anyway), the owning DAG's
        settled-ref watcher sees the death, and replayable DAGs recover
        while non-replayable ones fail typed exactly as a kill would."""
        try:
            await self._drain_push_objects()
        except Exception:  # noqa: BLE001 — migration is best-effort
            logger.exception("raylet %s object migration failed",
                             self.node_name)
        window = max(0.0, self._drain_deadline - time.time())
        shed_at = self._drain_deadline - min(2.0, 0.25 * window)
        shed_done = False
        last_log = 0.0
        while not self._stopped and time.time() < self._drain_deadline:
            leased = [h for h in self.workers.values() if h.leased]
            if not leased:
                break
            pinned = [h for h in leased if h.dag_pins]
            if time.time() - last_log > 1.0:
                last_log = time.time()
                logger.info(
                    "raylet %s draining: %d leased worker(s), %d of them "
                    "DAG-pinned (%s)", self.node_name, len(leased),
                    len(pinned),
                    sorted({d for h in pinned for d in h.dag_pins}))
            if pinned and len(pinned) == len(leased) and not shed_done \
                    and time.time() >= shed_at:
                # Only DAG pins stand between this node and
                # drain_complete: shed them instead of wedging until the
                # deadline. Dropping the accounting first keeps
                # rpc_dag_lease_accounting truthful while the shutdowns
                # land.
                shed_done = True
                logger.warning(
                    "raylet %s draining: shedding %d DAG-pinned "
                    "worker(s) whose owning DAG did not migrate",
                    self.node_name, len(pinned))
                for h in pinned:
                    for dag_id in list(h.dag_pins):
                        pins = self._dag_pins.get(dag_id)
                        if pins is not None:
                            pins.discard(h.worker_id.hex())
                            if not pins:
                                self._dag_pins.pop(dag_id, None)
                    h.dag_pins.clear()
                    asyncio.ensure_future(self._push_shutdown(h))
            await asyncio.sleep(0.1)
        if self._stopped:
            return
        try:
            await self.gcs_conn.request("drain_complete",
                                        {"node_id": self.node_id})
        except rpc.RpcError:
            pass

    async def _drain_push_objects(self):
        """Push sealed copies this node is the SOLE live holder of to a
        live peer and register the new location with the object's owner,
        so no owner ever needs lineage reconstruction for this
        (about-to-die) node. Copies another live node already holds are
        skipped — under a tight preemption deadline, re-copying cached
        secondaries would crowd out the sole-copy primaries that actually
        need saving."""
        peers = [v["address"] for v in self.cluster_view.values()
                 if v.get("address")]
        if not peers:
            return
        peer_set = set(peers)
        moved = 0
        for oid in list(self.store.objects):
            ent = self.store.objects.get(oid)
            if ent is None or not self.store.contains(oid):
                continue
            if ent.owner_address:
                try:
                    info = await self.clients.request(
                        ent.owner_address, "owner_locate",
                        {"object_id": ObjectID(oid), "timeout": 0.5},
                        timeout=2.0)
                except (rpc.RpcError, OSError):
                    info = None  # owner unreachable: assume sole copy
                if isinstance(info, dict):
                    if info.get("inline") is not None:
                        continue  # owner holds the value inline: safe
                    if any(loc in peer_set
                           for loc in info.get("locations", [])):
                        continue  # a live peer already has a copy
            remaining = self._drain_deadline - time.time()
            if remaining <= 0:
                # Deadline exhausted: anything left unsaved is lost to
                # lineage reconstruction — stop burning the grace window.
                logger.warning("raylet %s drain deadline hit mid-migration",
                               self.node_name)
                break
            target = peers[moved % len(peers)]
            ent2 = self.store.objects.get(oid)
            size = ent2.size if ent2 is not None else 0
            try:
                if size > self.config.object_transfer_chunk_bytes:
                    # Large object: have the peer PULL it through the
                    # object-manager chunked transfer path (bounded
                    # frames — _MAX_MSG no longer caps drainable object
                    # size), rate-limited against the drain deadline.
                    ok = await self.clients.request(
                        target, "store_fetch_remote", {
                            "object_id": oid, "locations": [self.address],
                            "owner_address": ent.owner_address},
                        timeout=max(1.0, remaining))
                    if not ok:
                        continue
                else:
                    desc = self.store.pin(oid)
                    if desc is None:
                        continue
                    try:
                        seg, offset, sz, metadata = desc
                        data = bytes(self.store.view(seg, offset, sz))
                    finally:
                        self.store.unpin(oid)
                    await self.clients.request(target, "store_put_bytes", {
                        "object_id": oid, "data": data,
                        "metadata": metadata,
                        "owner_address": ent.owner_address},
                        timeout=max(1.0, min(30.0, remaining)))
            except (rpc.RpcError, OSError):
                continue
            moved += 1
            if ent.owner_address:
                try:
                    conn = await self.clients.get(ent.owner_address)
                    await conn.notify("owner_add_location", {
                        "object_id": ObjectID(oid), "location": target})
                except Exception:  # noqa: BLE001 — owner may be gone
                    pass
        if moved:
            logger.info("raylet %s migrated %d primary copies before drain",
                        self.node_name, moved)

    # ------------------------------------------------------------------
    # Lease protocol (normal tasks)

    @rpc.non_idempotent
    async def rpc_request_worker_lease(self, conn, payload):
        """Grant local worker(s), queue, or spill to another node.

        `count` is the client's backlog hint (queued tasks of this sched
        class): the reply carries up to `count` grants in ONE round trip
        (reference: direct_task_transport.h lease pipelining), so N needed
        workers cost ~1 RPC instead of N.

        Reply: {"granted": {...}, "grants": [{...}, ...]}
             | {"spillback": address} | {"infeasible": True} | {"retry": True}
        """
        spec: TaskSpec = payload["spec"]
        count = max(1, int(payload.get("count", 1)))
        if self._draining:
            # Drain phase 1: no new grants here. Spill to a live peer when
            # one could take the shape; otherwise ask the client to retry
            # (it re-routes once the cluster view catches up). Past the
            # deadline this node is as good as dead — fail fast so clients
            # stop dialing it.
            target = self._drain_spill_target(spec.resources)
            if target is not None:
                return {"spillback": target}
            if time.time() > self._drain_deadline:
                return {"infeasible": True, "drained": True,
                        "why": (f"node {self.node_name} was drained and "
                                "no live peer can take the lease")}
            return {"retry": True, "draining": True}
        if self._container_env(spec) is not None:
            from ray_tpu._private import runtime_env_container as _rec
            if not _rec.runner_available():
                return {"infeasible": True,
                        "why": ("container runtime env needs podman or "
                                "docker on the node (or a "
                                "RAY_TPU_CONTAINER_RUNNER hook); none "
                                "found")}
        pg_key = None
        if spec.scheduling.placement_group_id is not None:
            idx = spec.scheduling.bundle_index
            if idx < 0:
                # any bundle of the PG on this node
                for key in self.pool.bundles:
                    if key[0] == spec.scheduling.placement_group_id.binary():
                        pg_key = key
                        break
                if pg_key is None:
                    return {"infeasible": True}
            else:
                pg_key = (spec.scheduling.placement_group_id.binary(), idx)
                if pg_key not in self.pool.bundles:
                    return {"infeasible": True}

        if pg_key is None and spec.scheduling.kind == "DEFAULT":
            # Distributed decision: pick best node from the synced view.
            best = self._pick_best_node(spec.resources)
            if best is not None and best != self.node_id:
                view = self.cluster_view.get(best)
                if view and view.get("address"):
                    return {"spillback": view["address"]}
                # fall through to local queue if address unknown
            if best is None and not self.pool.feasible(spec.resources):
                # Nothing available anywhere; spill to a node where the
                # request is at least feasible by its total resources.
                for node_id, view in self.cluster_view.items():
                    total = view.get("total", {})
                    if view.get("address") and all(
                            total.get(k, 0) >= v
                            for k, v in spec.resources.items() if v > 0):
                        return {"spillback": view["address"]}
                if not self._autoscaler_active:
                    return {"infeasible": True}
                # Autoscaler live: queue the request so the heartbeat
                # reports it as demand and a new node can absorb it
                # (reference: infeasible tasks wait + warn, they don't
                # fail, cluster_task_manager.cc).
        elif pg_key is None and spec.scheduling.kind == "SPREAD":
            best = self._pick_spread_node(spec.resources)
            if best is not None and best != self.node_id:
                view = self.cluster_view.get(best)
                if view and "address" in view:
                    return {"spillback": view["address"]}
        elif pg_key is None and spec.scheduling.kind == "NODE_AFFINITY":
            if spec.scheduling.node_id != self.node_id:
                view = self.cluster_view.get(spec.scheduling.node_id)
                if view and "address" in view:
                    return {"spillback": view["address"]}
                if not spec.scheduling.soft:
                    return {"infeasible": True}
        elif pg_key is None and spec.scheduling.kind == "NODE_LABEL":
            # Label-constrained placement (reference:
            # NodeLabelSchedulingStrategy): hard must match the executing
            # node; soft prefers matching nodes among the eligible;
            # availability outranks soft preference (a preference must
            # not route onto a saturated node past an idle eligible one).
            hard = spec.scheduling.labels_hard or {}
            soft = spec.scheduling.labels_soft or {}
            local_ok = (_labels_match(self.labels, hard)
                        and self.pool.feasible(spec.resources))
            local_soft = local_ok and (not soft
                                       or _labels_match(self.labels, soft))
            if not local_soft:
                target = self._label_spill_target(
                    spec.resources, hard, soft,
                    # a feasible local node only yields to a peer that is
                    # BOTH soft-matching and immediately available
                    need_beat_local=local_ok)
                if target is not None:
                    return {"spillback": target}
            if not local_ok:
                if self._autoscaler_active:
                    pass  # queue: demand heartbeat lets a labeled node spawn
                else:
                    return {"infeasible": True,
                            "why": (f"no node satisfies label constraints "
                                    f"hard={hard} (and resources "
                                    f"{spec.resources})")}

        fut = asyncio.get_running_loop().create_future()
        req = PendingLease(spec, pg_key, fut, conn, count)
        self._pending_leases.append(req)
        self._watch_lease_client(conn)
        self._try_dispatch()
        self._ensure_worker_supply()
        try:
            return await asyncio.wait_for(fut, self.config.worker_lease_timeout_s)
        except asyncio.TimeoutError:
            try:
                self._pending_leases.remove(req)
            except ValueError:
                pass
            return {"retry": True}

    def _label_spill_target(self, resources: dict, hard: dict, soft: dict,
                            need_beat_local: bool = False):
        """Best peer for a label-constrained request, or None.

        Ranking (higher wins): soft-matching AND available(4) >
        hard-only available(3) > soft-matching feasible-by-totals(2) >
        hard-only feasible(1). With need_beat_local (the local node can
        already run it), only rank-4 peers justify a hop."""
        def fits(view, key):
            caps = view.get(key, {})
            return all(caps.get(k, 0) >= v
                       for k, v in resources.items() if v > 0)

        best_rank, best_addr = 0, None
        for _nid, view in self.cluster_view.items():
            if not view.get("address"):
                continue
            labels = view.get("labels", {})
            if not _labels_match(labels, hard):
                continue
            soft_ok = bool(soft) and _labels_match(labels, soft)
            if fits(view, "available"):
                rank = 4 if soft_ok else 3
            elif fits(view, "total"):
                rank = 2 if soft_ok else 1
            else:
                continue
            if rank > best_rank:
                best_rank, best_addr = rank, view["address"]
        if need_beat_local and best_rank < 4:
            return None
        return best_addr

    @rpc.idempotent
    async def rpc_announce_client(self, conn, payload):
        """Core workers identify themselves right after connecting so a
        later disconnect maps back to their owner address (driver OR
        worker: nested-task submitters get the same reclamation)."""
        self._conn_owner[conn] = payload.get("owner_address", "")
        self._watch_lease_client(conn)
        return True

    def _watch_lease_client(self, conn):
        """Reclaim a client's leases when its raylet connection closes
        (clean shutdown or crash). Leased non-actor workers are killed —
        any task still running on them is orphaned (reference: job exit
        destroys its leased workers, worker_pool.cc DisconnectClient);
        the client's non-detached ACTORS are killed via the GCS
        owner-death notification (detached actors survive)."""
        if conn is None or conn in self._lease_conns:
            return
        if getattr(conn, "closed", False):
            # Lost the race: the conn died before we could watch it.
            asyncio.ensure_future(self._reclaim_client_leases(conn))
            return
        self._lease_conns.add(conn)
        prev = conn.on_close

        def _on_close(c, _prev=prev):
            self._lease_conns.discard(conn)
            asyncio.ensure_future(self._reclaim_client_leases(conn))
            if _prev:
                _prev(c)

        conn.on_close = _on_close

    async def _reclaim_client_leases(self, conn):
        # Pending (ungranted) requests from the dead client must not be
        # granted to nobody: cancel their futures.
        for req in self._pending_leases:
            if req.conn is conn and not req.fut.done():
                req.fut.cancel()
        self._pending_leases = [
            e for e in self._pending_leases if not e.fut.done()]
        for handle in list(self.workers.values()):
            if not (handle.leased and handle.lease_conn is conn):
                continue
            if handle.is_actor_worker:
                continue
            handle.leased = False
            handle.lease_conn = None
            self.pool.release(handle.lease_resources, handle.lease_pg)
            self._mark_resources_dirty()
            handle.lease_resources = {}
            handle.lease_pg = None
            try:
                if handle.conn:
                    await handle.conn.push("shutdown", {})
            except Exception:
                pass
        owner = self._conn_owner.pop(conn, "")
        if owner:
            # Non-detached actors owned by the departed client die with
            # it (reference: gcs_actor_manager OnWorkerDead).
            try:
                await self.gcs_conn.request("owner_disconnected",
                                            {"owners": [owner]})
            except rpc.RpcError:
                pass
        self._try_dispatch()

    def _try_dispatch(self):
        if self._draining:
            # No grants during drain; bounce anything still queued.
            for req in self._pending_leases:
                if not req.fut.done():
                    req.fut.set_result({"retry": True})
            self._pending_leases.clear()
            return
        if not self._pending_leases:
            return
        remaining = []
        n_waiting = sum(1 for e in self._pending_leases
                        if not e.fut.done())
        idle0 = len(self._pools)
        for req in self._pending_leases:
            fut = req.fut
            if fut.done():
                continue
            spec, pg_key, count = req.spec, req.pg_key, req.count
            if not self.pool.fits(spec.resources, pg_key):
                # Re-evaluate spillback for queued requests: the entry-time
                # decision can race with concurrent grants that drained the
                # local pool (reference: each scheduling tick may spill,
                # cluster_task_manager.h). PG-pinned and affinity tasks
                # never spill.
                if pg_key is None and spec.scheduling.kind in ("DEFAULT",
                                                               "SPREAD"):
                    for node_id, view in self.cluster_view.items():
                        avail = view.get("available", {})
                        if view.get("address") and all(
                                avail.get(k, 0) >= v
                                for k, v in spec.resources.items() if v > 0):
                            # Debit our local copy of the peer's view so a
                            # burst of queued requests doesn't all spill to
                            # the same (about-to-be-full) node; the next
                            # resource pub refreshes the real numbers.
                            for k, v in spec.resources.items():
                                if v > 0:
                                    avail[k] = avail.get(k, 0) - v
                            fut.set_result(
                                {"spillback": view["address"]})
                            break
                if not fut.done():
                    remaining.append(req)
                continue
            # Fair multi-grant: one client's backlog hint must not soak
            # every idle worker while other clients' requests wait.
            cap = count
            if n_waiting > 1:
                cap = max(1, min(count, idle0 // n_waiting))
            grants = []
            while len(grants) < cap and self.pool.fits(spec.resources,
                                                       pg_key):
                worker = self._get_idle_worker(
                    req.env_hash,
                    exact=req.container_env is not None,
                    record=not req.demand_recorded,
                    demand_n=req.count)
                req.demand_recorded = True
                if worker is None:
                    break
                self.pool.acquire(spec.resources, pg_key)
                worker.leased = True
                worker.lease_owner = spec.owner_address
                if req.env_hash:
                    worker.env_hash = req.env_hash
                worker.lease_class = req.sched_class
                worker.lease_resources = dict(spec.resources)
                worker.lease_pg = pg_key
                worker.lease_conn = req.conn
                worker.idle_since = time.time()
                grants.append({
                    "worker_id": worker.worker_id,
                    "worker_address": worker.address,
                    "node_id": self.node_id,
                })
            if not grants:
                remaining.append(req)
                continue
            self._mark_resources_dirty()
            fut.set_result({"granted": grants[0], "grants": grants})
        self._pending_leases = [e for e in remaining if not e.fut.done()]
        self._ensure_worker_supply()

    @rpc.idempotent
    async def rpc_return_worker(self, conn, payload):
        """Lease released by the submitter (idle timeout or task class change)."""
        worker_id = payload["worker_id"]
        handle = self.workers.get(worker_id)
        if handle is None or not handle.leased:
            return False
        handle.leased = False
        handle.lease_conn = None
        self.pool.release(handle.lease_resources, handle.lease_pg)
        self._mark_resources_dirty()
        handle.lease_resources = {}
        handle.lease_pg = None
        if payload.get("kill", False):
            try:
                if handle.conn:
                    await handle.conn.push("shutdown", {})
            except Exception:
                pass
        else:
            handle.idle_since = time.time()
            self._offer_idle_worker(handle)
        self._try_dispatch()
        return True

    def _pick_best_node(self, resources: Dict[str, float]) -> Optional[NodeID]:
        """Hybrid pack/spread over local + synced cluster view."""
        candidates: List[tuple] = []
        if self.pool.fits(resources):
            candidates.append((self.node_id, self._utilization(
                self.pool.available, self.pool.total)))
        for node_id, view in self.cluster_view.items():
            if node_id == self.node_id:
                continue
            avail, total = view["available"], view["total"]
            if all(avail.get(k, 0) >= v for k, v in resources.items() if v > 0):
                candidates.append((node_id, self._utilization(avail, total)))
        if not candidates:
            return None
        thr = self.config.scheduler_spread_threshold
        packed = [c for c in candidates if c[1] < thr]
        # Prefer local when tied (locality, lease reuse).
        def keyfn(c):
            return (-c[1], c[0] != self.node_id)
        if packed:
            return min(packed, key=keyfn)[0]
        return min(candidates, key=lambda c: (c[1], c[0] != self.node_id))[0]

    def _pick_spread_node(self, resources) -> Optional[NodeID]:
        candidates = []
        if self.pool.fits(resources):
            candidates.append((self.node_id,
                               self._utilization(self.pool.available, self.pool.total)))
        for node_id, view in self.cluster_view.items():
            if node_id == self.node_id:
                continue
            if all(view["available"].get(k, 0) >= v
                   for k, v in resources.items() if v > 0):
                candidates.append((node_id,
                                   self._utilization(view["available"], view["total"])))
        if not candidates:
            return None
        return min(candidates, key=lambda c: c[1])[0]

    @staticmethod
    def _utilization(avail: Dict[str, float], total: Dict[str, float]) -> float:
        fracs = [1 - avail.get(k, 0) / t for k, t in total.items() if t > 0]
        return max(fracs) if fracs else 0.0

    # ------------------------------------------------------------------
    # Actor creation (GCS -> this raylet)

    @rpc.non_idempotent
    async def rpc_create_actor(self, conn, payload):
        """Create-by-actor-id dedupe in front of the real create: a GCS
        restored from a snapshot re-drives PENDING creations, and the
        original create may STILL be running on this raylet (hung
        constructor, slow worker spawn) — or may have completed with its
        reply lost to the dead GCS connection. Either way a second
        instantiation of the same (actor_id, restart-epoch) would leak a
        worker + double the actor's side effects; instead the re-drive
        joins the in-flight create or returns the already-hosted
        instance."""
        spec: TaskSpec = payload["spec"]
        epoch = payload.get("num_restarts", 0)
        key = (spec.actor_id.binary(), epoch)
        for w in self.workers.values():
            if (getattr(w, "is_actor_worker", False) and w.leased
                    and w.actor_id == spec.actor_id
                    and getattr(w, "actor_epoch", -1) == epoch):
                return {"actor_address": w.address, "worker_id": w.worker_id}
        inflight = self._creating_actors.get(key)
        if inflight is not None:
            # Shielded: the joiner's own cancellation must not cancel the
            # original create it merely observes.
            return await asyncio.shield(inflight)
        fut = asyncio.get_event_loop().create_future()
        # A joiner may never materialize; don't warn on an unretrieved
        # create failure (the original caller gets it raised directly).
        fut.add_done_callback(
            lambda f: f.exception() if not f.cancelled() else None)
        self._creating_actors[key] = fut
        try:
            result = await self._create_actor(spec, payload, epoch)
            if not fut.done():
                fut.set_result(result)
            return result
        except BaseException as e:
            if not fut.done():
                if isinstance(e, asyncio.CancelledError):
                    fut.cancel()
                else:
                    fut.set_exception(e)
            raise
        finally:
            self._creating_actors.pop(key, None)

    async def _create_actor(self, spec: TaskSpec, payload, epoch: int):
        if self._draining:
            # The GCS already excludes draining nodes from placement; this
            # covers the race where the pick happened pre-drain.
            raise RuntimeError("node is draining; actor must go elsewhere")
        cenv = self._container_env(spec)
        if cenv is not None:
            from ray_tpu._private import runtime_env_container as _rec
            if not _rec.runner_available():
                raise RuntimeError(
                    "container runtime env needs podman or docker on the "
                    "node (or a RAY_TPU_CONTAINER_RUNNER hook); none found")
        pg_key = None
        if spec.scheduling.placement_group_id is not None:
            idx = max(0, spec.scheduling.bundle_index)
            pg_key = (spec.scheduling.placement_group_id.binary(), idx)
        if not self.pool.acquire(spec.resources, pg_key):
            raise RuntimeError("resources no longer available for actor")
        from ray_tpu.util import metrics as _metrics
        trace = f"actor:{spec.actor_id.hex()}"
        # The pool charge belongs to this coroutine throughout the try
        # below (a worker lease only takes it over AFTER the try, or —
        # in the register-reply race — via the fut inspected in the
        # handler). CancelledError can land at ANY await inside — it is
        # a BaseException, so ordinary failure-branch releases never
        # see it — and without this handler a create cancelled
        # mid-prefetch or mid-spawn-wait (GCS connection death) charged
        # the node forever.
        fut: Optional[asyncio.Future] = None
        try:
            function_blob = await self._prefetch_function(spec.function_id)
            # t0 AFTER the blob prefetch: the spawn histogram/span
            # measures the wait for a worker, not the (first-create-only)
            # KV fetch.
            t0 = time.time()
            worker = self._get_idle_worker(spec.env_hash(),
                                           exact=cenv is not None)
            result_fut: Optional[asyncio.Future] = None
            mode = "warm" if worker is not None else "cold"
            if worker is None:
                self._spawn_worker(container_env=cenv)
                # FIFO hand-off: freshly registered workers go to the
                # OLDEST waiting create (rpc_register_worker serves this
                # queue). Polling here instead let N concurrent creates
                # steal each other's spawns — under a 40-actor storm on
                # one node some handlers starved to the timeout
                # (measured: 4s -> 240s). The waiter carries the SPEC so
                # registration can dispatch the assignment in its reply
                # (no idle→re-offer round trip).
                fut = asyncio.get_event_loop().create_future()
                waiter = _ActorWorkerWaiter(spec.env_hash(),
                                            cenv is not None,
                                            fut, spec, epoch, pg_key,
                                            function_blob)
                self._actor_worker_waiters.append(waiter)
                got = None
                try:
                    got = await asyncio.wait_for(
                        fut, timeout=self.config.worker_start_timeout_s)
                except asyncio.TimeoutError:
                    pass
                finally:
                    if waiter in self._actor_worker_waiters:
                        self._actor_worker_waiters.remove(waiter)
                if got is not None:
                    _kind, worker, result_fut = got
                else:
                    # Last chance: a worker freed via the idle path (the
                    # request was already counted by the first attempt).
                    worker = self._get_idle_worker(spec.env_hash(),
                                                   exact=cenv is not None,
                                                   record=False)
                if worker is None:
                    raise RuntimeError("worker failed to start for actor")
        except BaseException:
            served = None
            if fut is not None and fut.done() and \
                    not fut.cancelled() and fut.exception() is None:
                # ray-tpu: noqa(ASYNC-BLOCK): asyncio future, done() checked above — result() is a non-blocking read here
                served = fut.result()
            if served is not None and served[0] == "dispatched":
                # A registration raced the cancellation and already
                # leased the worker against this charge: undo it
                # exactly like a failed instantiate (leased flag
                # keeps the release single-shot).
                w = served[1]
                self._instantiate_results.pop(w.worker_id, None)
                self._unlease_failed_create(w, spec, pg_key)
            elif served is not None:
                # Idle rescue raced the cancellation: the worker was
                # handed over UNLEASED — give back the charge and
                # return the worker to its pool.
                self.pool.release(spec.resources, pg_key)
                self._offer_idle_worker(served[1])
            else:
                self.pool.release(spec.resources, pg_key)
            raise
        # From here the charge is (or is about to be) owned by a worker
        # lease: register-reply dispatch leased at registration, and the
        # warm path leases synchronously below before the next await —
        # every later failure releases via _unlease_failed_create's
        # leased-flag gate, never via pool_owned.
        t_worker = time.time()
        _metrics.Histogram(
            "ray_tpu_worker_spawn_seconds",
            "how long an actor create waited for its worker "
            "(Mode=warm: pool hit; Mode=cold: process boot)",
            tag_keys=("Mode",)).observe(t_worker - t0, tags={"Mode": mode})
        self._record_span(trace, "actor:spawn", t0, t_worker)
        if result_fut is None:
            # Warm pool hit / idle rescue: lease here and dispatch the
            # constructor over the worker's RPC server.
            self._lease_worker_for_actor(worker, spec, pg_key)
            t_ctor = time.time()
            self._record_span(trace, "actor:register", t_worker, t_ctor)
            inst_payload = {"spec": spec,
                            "num_restarts": payload.get("num_restarts", 0)}
            if function_blob is not None:
                inst_payload["function_blob"] = function_blob
            try:
                if worker.conn is not None and not worker.conn.closed:
                    # Dispatch over the worker's registration connection
                    # (one push + one result request) — no per-create
                    # dial; a warm storm costs zero new TCP connections.
                    result_fut = asyncio.get_event_loop().create_future()
                    self._instantiate_results[worker.worker_id] = \
                        result_fut
                    await worker.conn.push("instantiate_actor",
                                           inst_payload)
                    reply = await asyncio.wait_for(
                        result_fut,
                        timeout=self.config.worker_start_timeout_s)
                else:
                    reply = await self.clients.request(
                        worker.address, "instantiate_actor", inst_payload,
                        timeout=self.config.worker_start_timeout_s)
            except BaseException:
                self._instantiate_results.pop(worker.worker_id, None)
                self._unlease_failed_create(worker, spec, pg_key)
                raise
        else:
            # Register-reply dispatch: the lease and the instantiate
            # payload rode the registration reply; await the outcome.
            t_ctor = t_worker
            self._record_span(trace, "actor:register", t_worker, t_ctor)
            try:
                reply = await asyncio.wait_for(
                    result_fut, timeout=self.config.worker_start_timeout_s)
            except BaseException:
                self._instantiate_results.pop(worker.worker_id, None)
                self._unlease_failed_create(worker, spec, pg_key)
                raise
        self._record_span(trace, "actor:ctor", t_ctor, time.time())
        if isinstance(reply, dict) and reply.get("app_error"):
            # Constructor raised: the worker is still healthy — return it
            # to the idle pool (without this it would leak, unleasable,
            # one process per attempt) and surface the error to the GCS
            # as data.
            self._unlease_failed_create(worker, spec, pg_key)
            worker.idle_since = time.time()
            self._offer_idle_worker(worker)
            self._mark_resources_dirty()
            return {"app_error": reply["app_error"]}
        # Stamp the epoch only on a COMPLETED create: the dedupe fast
        # path must never hand out the address of a worker whose
        # constructor is still running (a re-driven create joins the
        # in-flight future instead and replies post-construction).
        worker.actor_epoch = epoch
        return {"actor_address": worker.address, "worker_id": worker.worker_id}

    def _unlease_failed_create(self, worker: WorkerHandle, spec: TaskSpec,
                               pg_key: Optional[tuple]):
        if worker.leased:
            # `leased` gates the release on BOTH failure paths (here and
            # _on_worker_disconnect): whichever runs first releases, the
            # other no-ops.
            self.pool.release(spec.resources, pg_key)
        worker.leased = False
        worker.is_actor_worker = False
        worker.actor_id = None

    def _prestart_workers(self):
        """Warm the pool so first leases don't wait on worker boot
        (reference: WorkerPool prestart, worker_pool.h)."""
        if self._stopped or self._draining:
            return
        floor = min(int(self.pool.total.get("CPU", 1)), 4,
                    self.config.max_workers_per_node - len(self.workers))
        supply = len(self._pools) + self._starting_workers
        self._spawn_workers(max(0, floor - supply))

    @rpc.idempotent
    async def rpc_prestart_workers(self, conn, payload):
        """Explicit warm-up hint (GCS creation batches, gang recovery,
        serve scale-ups): `count` worker acquisitions for `env_hash` are
        about to land on this node. Pins the pool floor for the hint's
        TTL and spawns the shortfall NOW as one multi-spawn batch, so the
        storm forks before its first create arrives. Container envs are
        not generically prestartable (the spawn needs the container
        spec); their hint still pins the floor so the reaper spares any
        dedicated workers already warm."""
        if self._draining or self._stopped:
            return 0
        count = max(0, int(payload.get("count", 0)))
        env_hash = payload.get("env_hash", "") or ""
        if count <= 0:
            return 0
        self.prestart_hints_received += count
        ttl_s = float(payload.get("ttl_s",
                                  self.config.prestart_hint_ttl_s))
        # merge=True: a replayed hint RPC must stay idempotent (per-env
        # max). fresh_alias: for a non-container env the workers this
        # hint spawns are GENERIC (they apply the env at first lease) and
        # idle in the fresh pool — the alias adds this hint to that
        # pool's floor (summed across envs, so two envs' batches both
        # survive the reaper).
        self._pools.hint(env_hash, count, ttl_s=ttl_s, merge=True,
                         fresh_alias=bool(env_hash)
                         and not payload.get("container"))
        if payload.get("container"):
            return 0
        sizes = self._pools.sizes()
        supply = (sizes.get(env_hash, 0) + self._starting_workers
                  + (sizes.get("", 0) if env_hash else 0))
        can_start = self.config.max_workers_per_node - len(self.workers)
        n = min(max(0, count - supply), max(0, can_start))
        self._spawn_workers(n)
        return n

    @rpc.idempotent
    async def rpc_kill_worker(self, conn, payload):
        handle = self.workers.get(payload["worker_id"])
        if handle is None:
            return False
        if handle.proc is not None:
            try:
                handle.proc.kill()
            except Exception:
                pass
        elif handle.pid > 0:
            try:
                os.kill(handle.pid, 9)
            except OSError:
                pass
        return True

    # ------------------------------------------------------------------
    # Placement group bundles

    @rpc.idempotent
    async def rpc_reserve_bundle(self, conn, payload):
        if self._draining:
            return False
        key = (payload["pg_id"].binary(), payload["bundle_index"])
        ok = self.pool.reserve_bundle(key, payload["resources"])
        if ok:
            self._mark_resources_dirty()
        return ok

    @rpc.idempotent
    async def rpc_return_bundle(self, conn, payload):
        key = (payload["pg_id"].binary(), payload["bundle_index"])
        self.pool.return_bundle(key)
        self._mark_resources_dirty()
        return True

    # ------------------------------------------------------------------
    # Object store service (workers on this node + remote raylets)

    @rpc.non_idempotent
    async def rpc_store_create(self, conn, payload):
        oid = payload["object_id"]
        res = self.store.create(oid, payload["size"],
                                payload.get("metadata", b""),
                                payload.get("owner_address", ""))
        self._track_creating(conn, oid)
        return res

    def _track_creating(self, conn, oid):
        """Abort CREATING entries whose writer dies before sealing.

        A worker that crashes between store_create and store_seal would
        otherwise leave the entry CREATING forever: readers block in
        wait_sealed until timeout and the region never returns to the
        free list. Tie the entry to the writer's connection — on close,
        abort whatever it never sealed (abort_create is a no-op for
        entries that did seal)."""
        pending = getattr(conn, "_store_creating", None)
        if pending is None:
            pending = set()
            conn._store_creating = pending
            prev = conn.on_close

            def _abort_unsealed(c, _prev=prev):
                for o in list(pending):
                    self.store.abort_create(o)
                pending.clear()
                if _prev:
                    _prev(c)

            conn.on_close = _abort_unsealed
        pending.add(oid)

    @rpc.idempotent
    async def rpc_store_seal(self, conn, payload):
        oid = payload["object_id"]
        self.store.seal(oid)
        pending = getattr(conn, "_store_creating", None)
        if pending is not None:
            pending.discard(oid)
        return True

    @rpc.idempotent
    async def rpc_store_abort(self, conn, payload):
        """Writer-side rollback of a CREATING entry (failed local write)."""
        oid = payload["object_id"]
        self.store.abort_create(oid)
        pending = getattr(conn, "_store_creating", None)
        if pending is not None:
            pending.discard(oid)
        return True

    @rpc.non_idempotent
    async def rpc_store_get(self, conn, payload):
        oid = payload["object_id"]
        timeout = payload.get("timeout")
        if not self.store.contains(oid):
            ok = await self.store.wait_sealed(oid, timeout)
            if not ok:
                return None
        desc = self.store.pin(oid)
        if desc is not None:
            # Same-node pin descriptor = a zero-copy view handed out.
            self.store.num_zero_copy_gets += 1
        return desc

    @rpc.non_idempotent
    async def rpc_store_release(self, conn, payload):
        self.store.unpin(payload["object_id"])
        return True

    @rpc.idempotent
    async def rpc_store_contains(self, conn, payload):
        return self.store.contains(payload["object_id"])

    @rpc.idempotent
    async def rpc_store_delete(self, conn, payload):
        for oid in payload["object_ids"]:
            self.store.delete(oid)
        return True

    @rpc.idempotent
    async def rpc_store_stats(self, conn, payload):
        return self.store.stats()

    @rpc.idempotent
    async def rpc_store_list(self, conn, payload):
        """Object inventory for the state API (`ray_tpu list objects`)."""
        out = []
        for oid, ent in list(self.store.objects.items()):
            out.append({"object_id": oid.hex(), "size": ent.size,
                        "pins": ent.pins, "state": ent.state,
                        "owner": ent.owner_address})
        return out

    @rpc.idempotent
    async def rpc_store_put_bytes(self, conn, payload):
        """Put raw serialized bytes (used by small-RPC path and transfers)."""
        self.store.write_and_seal(payload["object_id"], payload["data"],
                                  payload.get("metadata", b""),
                                  payload.get("owner_address", ""))
        return True

    # ---- inter-node transfer (object manager) ----

    @rpc.idempotent
    async def rpc_store_pull_chunk(self, conn, payload):
        """Serve one chunk of a local object to a remote raylet."""
        oid = payload["object_id"]
        offset = payload["offset"]
        length = payload["length"]
        desc = self.store.pin(oid)
        if desc is None:
            return None
        try:
            seg, obj_off, size, metadata = desc
            chunk = bytes(self.store.view(seg, obj_off + offset,
                                          min(length, size - offset)))
            return {"data": chunk, "total_size": size, "metadata": metadata}
        finally:
            self.store.unpin(oid)

    @rpc.idempotent
    async def rpc_store_fetch_remote(self, conn, payload):
        """Pull an object from a remote node into the local store."""
        oid = payload["object_id"]
        if self.store.contains(oid):
            return True
        if self.store.objects.get(oid) is not None:
            # A concurrent writer holds the entry mid-transfer — e.g. a
            # REPLAYED fetch racing its still-running original (handlers
            # are not cancelled when the requesting connection dies).
            # Racing create() would crash 'already exists'; wait for the
            # first writer's seal, and only fall through to fetch if it
            # aborted (entry rolled back) or stalled out.
            if await self.store.wait_sealed(oid, timeout=60.0):
                return True
            if self.store.contains(oid):
                return True
        locations: List[str] = payload["locations"]   # raylet addresses
        chunk_size = self.config.object_transfer_chunk_bytes
        for address in locations:
            if address == self.address:
                continue
            created = False
            try:
                first = await self.clients.request(
                    address, "store_pull_chunk",
                    {"object_id": oid, "offset": 0, "length": chunk_size},
                    timeout=30.0)
                if first is None:
                    continue
                total = first["total_size"]
                name, offset = self.store.create(oid, total,
                                                 first.get("metadata", b""),
                                                 payload.get("owner_address", ""))
                created = True
                view = self.store.view(name, offset, total)
                data = first["data"]
                view[: len(data)] = data
                pos = len(data)
                while pos < total:
                    part = await self.clients.request(
                        address, "store_pull_chunk",
                        {"object_id": oid, "offset": pos, "length": chunk_size},
                        timeout=30.0)
                    if part is None:
                        raise rpc.RpcError("object disappeared mid-transfer")
                    d = part["data"]
                    view[pos : pos + len(d)] = d
                    pos += len(d)
                self.store.seal(oid)
                return True
            except (rpc.RpcError, OSError):
                # RpcError or raw socket errors (ConnectionRefused when the
                # holder node died): try the next location.
                if created:
                    # Roll back so another location (or retry) can recreate.
                    self.store.abort_create(oid)
                continue
            except MemoryError:
                raise
        return False


def _labels_match(labels: dict, constraint: dict) -> bool:
    """Every constrained label must be present with an allowed value."""
    return all(labels.get(k) in v for k, v in constraint.items())
