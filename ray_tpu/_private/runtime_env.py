"""Runtime environments: per-task/actor env_vars, working_dir, py_modules.

Reference: python/ray/_private/runtime_env/ (plugin.py, packaging.py,
working_dir.py). Design here: packages are content-addressed zips in the
GCS KV ("packages" namespace). The driver zips local dirs at submission
time (cached per path), workers download + unpack into a node-local cache
directory and prepend it to sys.path; env_vars apply to the worker process
environment. Workers that applied a runtime env are dedicated to it — the
raylet only re-leases them to tasks with the same env hash (the reference
starts dedicated workers per env the same way, worker_pool.h).
"""

from __future__ import annotations

import asyncio
import hashlib
import io
import json
import os
import shutil
import sys
import tempfile
import zipfile
from typing import Dict, List, Optional, Tuple

MAX_PACKAGE_BYTES = 256 * 1024 * 1024
EXCLUDE_DIRS = {"__pycache__", ".git", ".hg", ".venv", "node_modules",
                ".eggs", ".mypy_cache", ".pytest_cache"}
_KNOWN_KEYS = {"env_vars", "working_dir", "py_modules", "pip", "container",
               "config", "_hash"}


def _default_cache_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "ray_tpu", "pkg_cache")


def package_dir(path: str) -> Tuple[str, bytes]:
    """Deterministically zip a directory; return (uri, zip_bytes).

    The uri is content-addressed (sha256 of the archive), so identical
    trees dedupe in the KV and in every node's cache.
    """
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env path {path!r} is not a directory")
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in EXCLUDE_DIRS)
        for f in sorted(files):
            full = os.path.join(root, f)
            entries.append((os.path.relpath(full, path), full))
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for rel, full in entries:
            try:
                with open(full, "rb") as fh:
                    data = fh.read()
            except OSError:
                continue
            total += len(data)
            if total > MAX_PACKAGE_BYTES:
                raise ValueError(
                    f"runtime_env package {path!r} exceeds "
                    f"{MAX_PACKAGE_BYTES >> 20} MiB")
            # Fixed ZipInfo date -> byte-identical archive for identical
            # trees -> stable content hash.
            z.writestr(zipfile.ZipInfo(rel), data)
    data = buf.getvalue()
    uri = "pkg://" + hashlib.sha256(data).hexdigest()[:32]
    return uri, data


def tree_signature(path: str) -> tuple:
    """Cheap stat-based change detector for a directory tree: (file count,
    total size, max mtime_ns). Used to invalidate the driver's per-path
    package cache without re-reading file contents."""
    count = total = mtime = 0
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d not in EXCLUDE_DIRS]
        for f in files:
            try:
                st = os.stat(os.path.join(root, f))
            except OSError:
                continue
            count += 1
            total += st.st_size
            mtime = max(mtime, st.st_mtime_ns)
    return (count, total, mtime)


def env_hash(env: dict) -> str:
    canon = json.dumps({k: v for k, v in env.items() if k != "_hash"},
                       sort_keys=True, default=str)
    return hashlib.sha1(canon.encode()).hexdigest()[:16]


def validate(env: Optional[dict]) -> Optional[dict]:
    """Validate + shallow-copy a user runtime_env dict (driver side)."""
    if not env:
        return None
    if not isinstance(env, dict):
        raise TypeError(f"runtime_env must be a dict, got {type(env)}")
    unknown = set(env) - _KNOWN_KEYS
    if unknown:
        raise ValueError(f"unsupported runtime_env keys: {sorted(unknown)} "
                         f"(supported: {sorted(_KNOWN_KEYS - {'_hash'})})")
    out = dict(env)
    ev = out.get("env_vars")
    if ev is not None:
        if (not isinstance(ev, dict)
                or not all(isinstance(k, str) and isinstance(v, str)
                           for k, v in ev.items())):
            raise TypeError("runtime_env['env_vars'] must be Dict[str, str]")
    wd = out.get("working_dir")
    if wd is not None and not isinstance(wd, str):
        raise TypeError("runtime_env['working_dir'] must be a path or pkg:// uri")
    pm = out.get("py_modules")
    if pm is not None and (not isinstance(pm, (list, tuple))
                           or not all(isinstance(p, str) for p in pm)):
        raise TypeError("runtime_env['py_modules'] must be a list of paths/uris")
    pip = out.get("pip")
    if pip is not None:
        # Accept ["pkg==1.0", ...] or {"packages": [...]} (reference
        # pip.py accepts both forms).
        if isinstance(pip, dict):
            pip = pip.get("packages", [])
        if (not isinstance(pip, (list, tuple))
                or not all(isinstance(p, str) for p in pip)):
            raise TypeError("runtime_env['pip'] must be a list of "
                            "requirement strings")
        out["pip"] = sorted(pip)
    cont = out.get("container")
    if cont is not None:
        # {"image": str, "run_options": [...], "python": str?} — the
        # raylet starts the worker INSIDE the image (runtime gate:
        # podman/docker must exist on the node; see
        # runtime_env_container.py). Workers themselves treat the key as
        # already satisfied.
        if isinstance(cont, str):
            cont = {"image": cont}
        if not isinstance(cont, dict) or not cont.get("image"):
            raise TypeError("runtime_env['container'] must be an image "
                            "name or {'image': ..., 'run_options': [...]}")
        ro = cont.get("run_options")
        if ro is not None and (not isinstance(ro, (list, tuple)) or
                               not all(isinstance(o, str) for o in ro)):
            raise TypeError("container run_options must be a list of "
                            "strings")
        out["container"] = dict(cont)
    return out


class RuntimeEnvManager:
    """Worker-side: download/unpack packages, apply env to THIS process.

    A worker applies at most one runtime env in its lifetime (the raylet
    dedicates it to that env's hash afterwards), so apply() mutates
    process state (os.environ, sys.path, cwd) without needing undo.
    """

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir or _default_cache_dir()
        self.applied_hash: Optional[str] = None

    async def ensure(self, env: Optional[dict], kv_fetch) -> None:
        """Apply `env` to this process. kv_fetch: async (key: str) -> bytes.

        Raises RuntimeEnvSetupError on any failure (missing package, bad
        zip); idempotent for the same env hash.
        """
        from ray_tpu import exceptions as exc
        if not env:
            return
        h = env.get("_hash") or env_hash(env)
        if self.applied_hash == h:
            return
        if self.applied_hash is not None:
            raise exc.RuntimeEnvSetupError(
                f"worker already dedicated to runtime env "
                f"{self.applied_hash}; got {h}")
        try:
            for k, v in (env.get("env_vars") or {}).items():
                os.environ[k] = v
            for uri in (env.get("py_modules") or []):
                root = await self._fetch_unpack(uri, kv_fetch)
                if root not in sys.path:
                    sys.path.insert(0, root)
            wd = env.get("working_dir")
            if wd:
                root = await self._fetch_unpack(wd, kv_fetch)
                if root not in sys.path:
                    sys.path.insert(0, root)
                os.chdir(root)
            pip = env.get("pip")
            if pip:
                await self._apply_pip(list(pip))
        except exc.RuntimeEnvSetupError:
            raise
        except Exception as e:  # noqa: BLE001
            raise exc.RuntimeEnvSetupError(
                f"runtime env setup failed: {type(e).__name__}: {e}") from e
        self.applied_hash = h

    async def _apply_pip(self, packages):
        """Build (or reuse) the venv for `packages` and prepend its
        site-packages to THIS worker's sys.path (reference: pip.py runtime
        envs; the venv build is the slow part and is content-cached).

        The default installer shells out to pip (needs network at deploy
        time); tests inject one via RAY_TPU_PIP_INSTALLER="module:attr".
        """
        import sys as _sys
        from ray_tpu._private.runtime_env_pip import PipEnvManager
        installer = None
        hook = os.environ.get("RAY_TPU_PIP_INSTALLER")
        if hook:
            mod_name, _, attr = hook.partition(":")
            import importlib
            installer = getattr(importlib.import_module(mod_name), attr)
        mgr = PipEnvManager(os.path.join(self.cache_dir, "pip_envs"),
                            installer=installer)
        loop = asyncio.get_running_loop()
        py = await loop.run_in_executor(None, mgr.ensure, list(packages))
        venv_dir = os.path.dirname(os.path.dirname(py))
        ver = f"python{_sys.version_info[0]}.{_sys.version_info[1]}"
        sp = os.path.join(venv_dir, "lib", ver, "site-packages")
        if sp not in _sys.path:
            _sys.path.insert(0, sp)

    async def _fetch_unpack(self, uri: str, kv_fetch) -> str:
        from ray_tpu import exceptions as exc
        if not uri.startswith("pkg://"):
            # Local path env on a single-node cluster (driver == worker
            # node): use the directory in place.
            if os.path.isdir(uri):
                return os.path.abspath(uri)
            raise exc.RuntimeEnvSetupError(
                f"runtime env uri {uri!r} is neither pkg:// nor a local dir")
        digest = uri[len("pkg://"):]
        final = os.path.join(self.cache_dir, digest)
        if os.path.isdir(final):
            return final
        data = await kv_fetch("pkg:" + digest)
        if data is None:
            raise exc.RuntimeEnvSetupError(
                f"package {uri} not found in cluster KV")
        # The extract runs on the executor: this coroutine runs on the
        # raylet/worker daemon loop, and archive extraction + tree
        # removal are unbounded file I/O — a large package would stall
        # heartbeats and lease grants for its whole unpack.
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self._unpack_sync, data,
                                          digest, final)

    def _unpack_sync(self, data: bytes, digest: str, final: str) -> str:
        os.makedirs(self.cache_dir, exist_ok=True)
        tmp = tempfile.mkdtemp(dir=self.cache_dir, prefix=digest + ".tmp")
        try:
            with zipfile.ZipFile(io.BytesIO(data)) as z:
                z.extractall(tmp)
            os.rename(tmp, final)  # atomic publish; loser cleans up below
        except OSError:
            if not os.path.isdir(final):
                raise
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        return final


def merge(base: Optional[dict], override: Optional[dict]) -> Optional[dict]:
    """Per-option override of a job-level default env (reference semantics:
    task env replaces keys wholesale except env_vars, which merge)."""
    if not base:
        return override
    if not override:
        return dict(base)
    out = dict(base)
    for k, v in override.items():
        if k == "env_vars" and base.get("env_vars"):
            ev = dict(base["env_vars"])
            ev.update(v or {})
            out[k] = ev
        else:
            out[k] = v
    out.pop("_hash", None)
    return out
