"""Central config registry.

Equivalent in capability to the reference's RayConfig macro registry
(src/ray/common/ray_config_def.h): every knob has a typed default and is
overridable per-process via ``RAY_TPU_<NAME>`` environment variables or a
cluster-wide ``system_config`` dict passed to ``init()``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any


def _env(name: str, default):
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    if isinstance(default, (list, dict)):
        return json.loads(raw)
    return raw


@dataclass
class Config:
    # --- serialization / object store ---
    # Objects smaller than this are inlined into RPC replies and the
    # in-process store rather than the shared-memory store.
    max_direct_call_object_size: int = 100 * 1024
    # Per-node shared-memory object store capacity (bytes).
    object_store_memory: int = 2 * 1024**3
    # Fraction of store that triggers LRU eviction/spill.
    object_store_high_watermark: float = 0.8
    # Directory for spilled objects; default under session dir.
    object_spilling_dir: str = ""
    # Chunk size for node-to-node object transfer.
    object_transfer_chunk_bytes: int = 8 * 1024 * 1024

    # --- scheduling ---
    # Hybrid policy: pack onto nodes until utilization crosses this
    # threshold, then spread (reference: hybrid_scheduling_policy.h).
    scheduler_spread_threshold: float = 0.5
    # Max worker leases a submitter requests in parallel per scheduling class.
    max_pending_lease_requests: int = 10
    # Tasks pushed to a leased worker without waiting for the previous reply
    # (the worker executes sequentially; pipelining hides the RPC round trip).
    task_pipeline_depth: int = 2
    # Queued tasks shipped per push RPC once pipelining engages (one round
    # trip covers the whole batch; also bounds head-of-line reply latency).
    # 64 with single-pool-job batch execution measured ~4x the task
    # throughput of 8; the fair-share split in _pump_queue still spreads a
    # burst across leases.
    task_batch_size: int = 64
    # Lease reuse idle timeout (s): a leased idle worker is returned after this.
    idle_worker_lease_timeout_s: float = 0.5
    worker_lease_timeout_s: float = 30.0

    # --- worker pool ---
    num_initial_workers: int = 0
    max_workers_per_node: int = 64
    worker_start_timeout_s: float = 60.0
    # Soft cap of started workers per node; more start on demand.
    prestart_workers: bool = True
    # Concurrent create_actor RPCs the GCS creation pipeline keeps in
    # flight PER RAYLET (a launch storm fans out pipelined, but one node
    # must not absorb an unbounded dial-in).
    gcs_create_actor_concurrency: int = 32
    # TTL of a prestart hint's warm-pool floor (reaper protection).
    prestart_hint_ttl_s: float = 30.0

    # --- health / fault tolerance ---
    # OOM defense: kill a leased worker when system memory usage crosses
    # the threshold (reference: memory_monitor.h memory_usage_threshold).
    memory_usage_threshold: float = 0.95
    memory_monitor_interval_s: float = 1.0
    heartbeat_interval_s: float = 0.5
    node_death_timeout_s: float = 5.0
    task_max_retries_default: int = 3
    actor_max_restarts_default: int = 0
    gcs_rpc_timeout_s: float = 30.0
    # External GCS state store (the Redis-equivalent): "host:port" of a
    # `ray_tpu kv-store` server. When set, the GCS persists its snapshot
    # there (keyed by gcs_storage_namespace) so a head restarted anywhere
    # can recover cluster state. Empty = file snapshot in the session dir.
    gcs_storage_address: str = ""
    gcs_storage_namespace: str = "default"

    # --- pubsub / sync ---
    resource_broadcast_interval_s: float = 0.2
    # Per-subscriber pubsub outbox cap (frames). A stalled subscriber's
    # backlog drops OLDEST past this bound (counted in
    # ray_tpu_pubsub_dropped_total) instead of growing GCS memory without
    # limit.
    pubsub_max_outbox: int = 2000

    # --- metrics / events ---
    task_events_enabled: bool = True
    task_events_max_buffer: int = 100_000
    metrics_report_interval_s: float = 2.0
    # Cluster time-series store (GCS-side ring buffers fed by the
    # per-process MetricsAgent delta frames). Retention/resolution set
    # the per-series slot count: default ~15 min at 5 s = 180 slots.
    tsdb_retention_s: float = 900.0
    tsdb_resolution_s: float = 5.0
    # Hard cardinality bound on stored series; past it new series are
    # dropped and counted in ray_tpu_tsdb_dropped_series_total.
    tsdb_max_series: int = 8192
    # Kill switch for per-process metrics shipping (bench A/B).
    metrics_agent_enabled: bool = True

    # --- logging ---
    log_to_driver: bool = True

    # --- system ---
    session_dir_root: str = "/tmp/ray_tpu_sessions"

    extra: dict = field(default_factory=dict)

    @classmethod
    def load(cls, overrides: dict | None = None) -> "Config":
        cfg = cls()
        for f in fields(cls):
            if f.name == "extra":
                continue
            setattr(cfg, f.name, _env(f.name, getattr(cfg, f.name)))
        if overrides:
            for k, v in overrides.items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
                else:
                    cfg.extra[k] = v
        return cfg

    def to_dict(self) -> dict[str, Any]:
        d = {f.name: getattr(self, f.name) for f in fields(self) if f.name != "extra"}
        d.update(self.extra)
        return d


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config.load()
    return _global_config


def set_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg
