"""Asyncio RPC layer: the control-plane transport for every daemon.

Plays the role of the reference's gRPC wrappers (src/ray/rpc/grpc_server.h,
grpc_client.h): request/response with correlation ids, one-way notifications,
and server->client pushes (used for pubsub long-poll equivalents). TCP with a
length-prefixed pickled envelope; payloads are plain Python structures.

Envelope: u32 length | pickle([kind, msg_id, method, payload])
    kind: 0=request 1=response 2=error-response 3=notify 4=push
          5=batch (payload = list of envelopes, dispatched in order)

A BATCH envelope packs every frame coalesced within one loop tick into a
single pickle + transport write: N concurrent clients cost the daemon
~O(loop ticks) of framing work instead of O(messages) (reference analogue:
gRPC stream batching in the raylet/GCS fan-in paths). The receiver unpacks
in order, so cross-frame ordering is exactly what the per-frame encoding
gave. RAY_TPU_RPC_BATCH=0 turns the send side off (legacy framing);
decoding always understands both.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import pickle
import random
import struct
import traceback
from typing import Any, Awaitable, Callable, Dict, Optional

logger = logging.getLogger(__name__)

REQUEST, RESPONSE, ERROR, NOTIFY, PUSH, BATCH = 0, 1, 2, 3, 4, 5

_MAX_MSG = 1 << 31

_BATCHING_DEFAULT = os.environ.get(
    "RAY_TPU_RPC_BATCH", "1").lower() not in ("0", "false", "no")

# Process-wide transport totals (frames vs writes is the fan-in batching
# health signal: frames/write >> 1 under load means coalescing works).
# `inflight_requests` counts outstanding request() awaits across every
# connection of the process — the transport-level pipeline depth.
_stats = {"frames": 0, "writes": 0, "bytes": 0, "batched_frames": 0,
          "inflight_requests": 0}


def transport_stats() -> dict:
    """Snapshot of this process's transport counters."""
    return dict(_stats)


def export_transport_metrics():
    """Publish the transport counters into util/metrics.py's registry so
    they ride the normal report loop to the GCS /metrics endpoint."""
    from ray_tpu.util import metrics
    for name, key in (("ray_tpu_rpc_frames_total", "frames"),
                      ("ray_tpu_rpc_writes_total", "writes"),
                      ("ray_tpu_rpc_bytes_total", "bytes"),
                      ("ray_tpu_rpc_batched_frames_total",
                       "batched_frames"),
                      ("ray_tpu_rpc_inflight_requests",
                       "inflight_requests")):
        metrics.Gauge(name, "rpc transport counter").set(float(_stats[key]))

# ---- deterministic race-shaking (reference: ray_config_def.h:838
# RAY_testing_asio_delay_us) ------------------------------------------------
# RAY_TPU_TESTING_RPC_DELAY_US="<method-glob>=<min_us>:<max_us>[,...]"
# delays the START of matching handlers by a uniform random amount, which
# also reorders concurrently-arriving messages — the asyncio analogue of
# running the C++ core under randomized asio delays.
_delay_spec: Optional[list] = None


def _load_delay_spec() -> list:
    import os
    spec = []
    raw = os.environ.get("RAY_TPU_TESTING_RPC_DELAY_US", "")
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        pat, _, rng = part.partition("=")
        lo, _, hi = rng.partition(":")
        try:
            spec.append((pat, int(lo), int(hi or lo)))
        except ValueError:
            logger.warning("bad RPC delay spec part %r", part)
    return spec


def _injected_delay(method: str) -> float:
    """Seconds of injected delay for this method (0.0 = none)."""
    global _delay_spec
    if _delay_spec is None:
        _delay_spec = _load_delay_spec()
    if not _delay_spec:
        return 0.0
    import fnmatch
    import random
    for pat, lo, hi in _delay_spec:
        if fnmatch.fnmatch(method, pat):
            return random.uniform(lo, hi) / 1e6
    return 0.0


class RpcError(Exception):
    pass


# ---- per-method idempotency annotations ------------------------------------
# Every server handler carries an explicit idempotency marker (enforced by
# scripts/check_rpc_idempotency.py). ClientPool.request consults the
# registry to decide whether a request that may have REACHED the peer can
# be replayed after a connection loss: idempotent methods always can;
# non-idempotent methods must not (replaying e.g. register_job or a task
# push would double-execute on a live peer that only dropped the
# connection). Requests that provably never left this process
# (ConnectionLost.sent is False) are safe to retry either way.
#
# The registry fills two ways: decorator side effects when a server module
# is imported, and a lazy source scan (_scan_source_annotations) for the
# processes that dial methods whose defining module they never import — a
# driver or worker pulls in core_worker but not gcs.py/raylet.py, and an
# empty registry there would silently fall back to replaying everything.

_IDEMPOTENCY: Dict[str, bool] = {}
_SOURCE_SCANNED = False


def _annotate(fn, flag: bool):
    name = fn.__name__
    if name.startswith("rpc_"):
        name = name[4:]
    elif name.startswith("_rpc_"):
        name = name[5:]
    fn._rpc_idempotent = flag
    # Import-time registration keys by the FUNCTION name (rpc_ prefix
    # stripped) — correct for every server whose wire names match its
    # method names. Servers that alias on the wire (client_*/serve_*)
    # are re-registered under the true wire name in RpcServer.register.
    # When two servers expose the same name the SAFER flag wins — a
    # client pool addresses both kinds of peer. A colliding PURE READ
    # therefore loses its replay; give it a distinct wire name instead
    # (kv_store's kv_store_get vs the raylet's pinning store_get).
    prev = _IDEMPOTENCY.get(name)
    _IDEMPOTENCY[name] = flag if prev is None else (prev and flag)
    return fn


def idempotent(fn):
    """Mark an rpc_* handler safe to execute more than once per logical
    request (pure reads, set-to-value writes, keyed upserts)."""
    return _annotate(fn, True)


def non_idempotent(fn):
    """Mark an rpc_* handler whose replay observably double-executes
    (counters, appends, spawns). ClientPool never replays these once the
    original request may have reached the peer."""
    return _annotate(fn, False)


def scan_handler_annotations(lines) -> list:
    """Line-walk one file's source: (handler_name, lineno, flag) per
    `async def rpc_*` / `_rpc_*`, flag None when unannotated.

    THE single parser for idempotency annotations — used by the lazy
    runtime registry fill below AND by scripts/check_rpc_idempotency.py,
    so the CI gate and the process that acts on the annotations can
    never read the source differently."""
    import re
    handler = re.compile(r"^\s*async def (_?rpc_[a-z0-9_]+)\(")
    annot = re.compile(r"^\s*@(?:rpc\.)?(idempotent|non_idempotent)\b")
    deco = re.compile(r"^\s*@")
    out = []
    for i, line in enumerate(lines):
        m = handler.match(line)
        if not m:
            continue
        flag = None
        j = i - 1
        while j >= 0 and deco.match(lines[j]):
            am = annot.match(lines[j])
            if am:
                flag = am.group(1) == "idempotent"
            j -= 1
        out.append((m.group(1), i + 1, flag))
    return out


# Wire-alias map for the source scan: servers that register handlers
# under a DIFFERENT wire name than the function-derived key. The scan
# (which never imports the server module, so it cannot observe
# RpcServer.register's authoritative aliasing) applies the module's
# template to every handler it finds in that file, e.g. ClientServer's
# rpc_connect -> wire "client_connect". Without this, a replay-capable
# remote thin client dialing `client_*` / `serve_*` would find no
# annotation and fall back to the legacy retry-once behavior — a
# double-execute hole for the non-idempotent mutating calls.
_WIRE_ALIAS_MODULES = {
    os.path.join("util", "client", "server.py"): "client_{name}",
    os.path.join("serve", "grpc_proxy.py"): "serve_{name}",
}


def _scan_source_annotations():
    """Fill the registry from package source without importing the server
    modules; runs once per process, lazily, on the first unknown-method
    lookup. Files listed in _WIRE_ALIAS_MODULES additionally register
    every handler under its aliased wire name."""
    global _SOURCE_SCANNED
    _SOURCE_SCANNED = True
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for dirpath, _dirs, files in os.walk(pkg):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                # ray-tpu: noqa(ASYNC-BLOCK): one-shot lazy registry fill, cached for the process lifetime
                with open(path, encoding="utf-8") as f:
                    lines = f.readlines()
            except OSError:
                continue
            rel = os.path.relpath(path, pkg)
            alias_tpl = _WIRE_ALIAS_MODULES.get(rel)
            for name, _lineno, flag in scan_handler_annotations(lines):
                if flag is None:
                    continue
                name = name[5:] if name.startswith("_rpc_") else name[4:]
                keys = [name]
                if alias_tpl is not None:
                    keys.append(alias_tpl.format(name=name))
                for key in keys:
                    prev = _IDEMPOTENCY.get(key)
                    _IDEMPOTENCY[key] = flag if prev is None \
                        else (prev and flag)


def idempotency_of(method: str) -> Optional[bool]:
    """True/False when the method is annotated, None when unknown (a
    handler outside the package, e.g. test doubles)."""
    flag = _IDEMPOTENCY.get(method)
    if flag is None and not _SOURCE_SCANNED:
        _scan_source_annotations()
        flag = _IDEMPOTENCY.get(method)
    return flag


class RemoteRpcError(RpcError):
    def __init__(self, method: str, err_type: str, message: str, tb: str):
        self.method = method
        self.err_type = err_type
        self.err_message = message
        self.remote_traceback = tb
        super().__init__(f"RPC {method} failed remotely: {err_type}: {message}\n{tb}")

    def __reduce__(self):
        # Default Exception reduction would replay self.args (1 string) into
        # the 4-arg __init__ and break unpickling wherever this instance is
        # embedded (e.g. inside a serialized task error).
        return (RemoteRpcError, (self.method, self.err_type, self.err_message,
                                 self.remote_traceback))


class ConnectionLost(RpcError):
    """Transport-level loss. `sent` records whether the request bytes may
    have reached the peer: False = provably never left this process (dial
    failure, connection already closed before the write), True = in
    flight when the connection died, so the peer MAY have executed it.
    Retry policies key off this (see ClientPool.request)."""

    def __init__(self, *args):
        super().__init__(*args)
        self.sent = False


async def _read_msg(reader: asyncio.StreamReader):
    header = await reader.readexactly(4)
    (length,) = struct.unpack("<I", header)
    if length > _MAX_MSG:
        raise RpcError(f"message too large: {length}")
    data = await reader.readexactly(length)
    return pickle.loads(data)


def _encode(kind: int, msg_id: int, method: str, payload: Any) -> bytes:
    body = pickle.dumps([kind, msg_id, method, payload], protocol=5)
    return struct.pack("<I", len(body)) + body


def _approx_payload_size(payload: Any, depth: int = 3) -> int:
    """Cheap lower-bound estimate of a payload's wire size, catching the
    case that matters: large bytes-like values (object data, chunks)
    nested a level or two deep. Everything else counts a flat 64 bytes —
    this gates batch flushing, not accounting."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return len(payload)
    if depth > 0:
        if isinstance(payload, dict):
            return 64 + sum(_approx_payload_size(v, depth - 1)
                            for v in payload.values())
        if isinstance(payload, (list, tuple)) and len(payload) < 1024:
            return 64 + sum(_approx_payload_size(v, depth - 1)
                            for v in payload)
    return 64


class Connection:
    """One live duplex connection; shared by client and server sides."""

    _ids = itertools.count(1)

    HIGH_WATER = 1 << 20  # drain (backpressure) only past this buffer size
    MAX_BATCH_FRAMES = 1024  # flush early past this many queued frames

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 push_handler: Optional[Callable] = None):
        self.reader = reader
        self.writer = writer
        self.push_handler = push_handler
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self.on_close: Optional[Callable] = None
        # Set by server loop: peer-provided identity metadata.
        self.peer_info: dict = {}
        # Frame coalescing: frames queued within one loop tick flush as a
        # single BATCH envelope (one pickle + one transport write), see
        # send_nowait. `batching=False` keeps the write coalescing but
        # encodes legacy per-frame envelopes (interop / kill switch).
        self._out: list = []
        self._out_est_bytes = 0  # rough payload bytes queued (see send)
        self._flush_scheduled = False
        self.batching = _BATCHING_DEFAULT
        # Transport counters (frames-per-write is the batching signal).
        self.frames_sent = 0
        self.writes = 0
        self.bytes_sent = 0
        self.batched_frames = 0

    @property
    def closed(self) -> bool:
        return self._closed

    def _account(self, nframes: int, nbytes: int):
        self.frames_sent += nframes
        self.writes += 1
        self.bytes_sent += nbytes
        _stats["frames"] += nframes
        _stats["writes"] += 1
        _stats["bytes"] += nbytes
        if nframes > 1:
            self.batched_frames += nframes
            _stats["batched_frames"] += nframes

    def send_nowait(self, kind: int, msg_id: int, method: str, payload: Any):
        """Send with adaptive coalescing: the first frame of a loop tick
        writes through immediately (no latency tax on serial
        request-reply), later frames of the same tick batch into ONE
        BATCH envelope — one pickle.dumps and one socket.send for the
        whole burst (per-frame pickling + headers were the residual
        per-message cost after round-2's write coalescing; a send syscall
        alone measured ~64 us on this box). Loop thread only.
        """
        if self._closed:
            raise ConnectionLost("connection closed")
        if self._flush_scheduled:
            self._out.append((kind, msg_id, method, payload))
            self._out_est_bytes += _approx_payload_size(payload)
            return
        data = _encode(kind, msg_id, method, payload)
        self.writer.write(data)
        self._account(1, len(data))
        self._flush_scheduled = True
        asyncio.get_running_loop().call_soon(self._flush)

    def push_nowait(self, method: str, payload: Any = None):
        """Fire-and-forget push without a coroutine (pubsub fan-out: one
        publish to N subscribers costs N queue appends, not N tasks)."""
        self.send_nowait(PUSH, 0, method, payload)

    def _flush(self):
        self._flush_scheduled = False
        if self._closed or not self._out:
            return
        frames, self._out = self._out, []
        self._out_est_bytes = 0
        if len(frames) == 1 or not self.batching:
            for fr in frames:
                self._write_frame(fr)
            return
        try:
            data = _encode(BATCH, 0, "", frames)
        except Exception:
            # One unpicklable payload must not poison its batch-mates:
            # degrade to per-frame encoding so only the culprit fails.
            for fr in frames:
                self._write_frame(fr)
            return
        if len(data) > _MAX_MSG:
            # The combined envelope exceeds the frame cap even though the
            # members individually may not: ship them per-frame.
            for fr in frames:
                self._write_frame(fr)
            return
        self.writer.write(data)
        self._account(len(frames), len(data))

    def _write_frame(self, frame):
        kind, msg_id, method, payload = frame
        try:
            data = _encode(kind, msg_id, method, payload)
        except Exception as e:  # noqa: BLE001 — per-frame fault isolation
            self._on_encode_error(kind, msg_id, method, e)
            return
        self.writer.write(data)
        self._account(1, len(data))

    def _on_encode_error(self, kind, msg_id, method, e: Exception):
        """A queued frame failed to pickle at flush time (the caller has
        already returned). Keep the failure scoped to that frame: a
        RESPONSE degrades to a remote ERROR so the requester is not left
        hanging; a REQUEST fails its local future; one-way frames drop."""
        logger.exception("failed to encode frame for %s", method)
        if kind == RESPONSE:
            try:
                data = _encode(ERROR, msg_id, method,
                               (method, type(e).__name__,
                                f"unpicklable reply: {e}", ""))
                self.writer.write(data)
                self._account(1, len(data))
            except Exception:  # noqa: BLE001
                pass
        elif kind == REQUEST:
            fut = self._pending.get(msg_id)
            if fut is not None and not fut.done():
                fut.set_exception(e)

    def write_backed_up(self) -> bool:
        """Transport write buffer past the high-water mark: the peer is
        not draining. Shared predicate for send()'s backpressure and the
        GCS pubsub's slow-subscriber detection."""
        transport = self.writer.transport
        return (transport is not None
                and transport.get_write_buffer_size() > self.HIGH_WATER)

    async def send(self, kind: int, msg_id: int, method: str, payload: Any):
        self.send_nowait(kind, msg_id, method, payload)
        if (len(self._out) >= self.MAX_BATCH_FRAMES
                or self._out_est_bytes > self.HIGH_WATER):
            # Bound the batch by frames AND (estimated) bytes: a same-tick
            # burst of large replies must not accumulate into one giant
            # pickle (worst case past _MAX_MSG, and 2x peak memory).
            self._flush()
            self._flush_scheduled = True  # later frames keep queueing
        if self.write_backed_up():
            await self.writer.drain()

    async def request(self, method: str, payload: Any = None,
                      timeout: Optional[float] = None) -> Any:
        msg_id = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        _stats["inflight_requests"] += 1
        try:
            await self.send(REQUEST, msg_id, method, payload)
            return await asyncio.wait_for(fut, timeout)
        finally:
            _stats["inflight_requests"] -= 1
            self._pending.pop(msg_id, None)

    async def notify(self, method: str, payload: Any = None):
        await self.send(NOTIFY, 0, method, payload)

    async def push(self, method: str, payload: Any = None):
        await self.send(PUSH, 0, method, payload)

    def abort(self, exc: Exception):
        if self._closed:
            return
        self._closed = True
        self._out.clear()
        self._out_est_bytes = 0
        for fut in self._pending.values():
            if not fut.done():
                lost = ConnectionLost(str(exc))
                # These requests were already written (or queued for the
                # transport): the peer may have executed them.
                lost.sent = True
                fut.set_exception(lost)
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close:
            try:
                self.on_close(self)
            except Exception:
                pass

    async def close(self):
        self.abort(ConnectionLost("closed"))

    def _dispatch_response(self, kind, msg_id, payload):
        fut = self._pending.get(msg_id)
        if fut is None or fut.done():
            return
        if kind == RESPONSE:
            fut.set_result(payload)
        else:
            method, err_type, message, tb = payload
            fut.set_exception(RemoteRpcError(method, err_type, message, tb))

    def _dispatch_client_frame(self, kind, msg_id, method, payload):
        if kind in (RESPONSE, ERROR):
            self._dispatch_response(kind, msg_id, payload)
        elif kind == PUSH and self.push_handler is not None:
            try:
                res = self.push_handler(method, payload)
                if asyncio.iscoroutine(res):
                    asyncio.ensure_future(res)
            except Exception:
                logger.exception("push handler failed for %s", method)

    async def client_loop(self):
        """Read loop for the client side of a connection."""
        try:
            while True:
                kind, msg_id, method, payload = await _read_msg(self.reader)
                if kind == BATCH:
                    # Sub-frames dispatch in order: a batch preserves
                    # exactly the per-frame delivery order.
                    for sub in payload:
                        self._dispatch_client_frame(*sub)
                else:
                    self._dispatch_client_frame(kind, msg_id, method, payload)
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            self.abort(e)
        except Exception as e:
            logger.exception("client loop error")
            self.abort(e)


Handler = Callable[[Connection, Any], Awaitable[Any]]


class RpcServer:
    def __init__(self, name: str = "server"):
        self.name = name
        self._handlers: Dict[str, Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set[Connection] = set()
        self.port: int = 0

    def register(self, method: str, handler: Handler):
        self._handlers[method] = handler
        # Authoritative idempotency registration: the decorator keys the
        # registry by the handler's FUNCTION name, which is wrong for
        # servers that alias on the wire (ClientServer's `client_<name>`,
        # GrpcProxyActor's `serve_unary`). Recording under the actual
        # wire name here makes the annotation effective for every pool /
        # reconnecting client living in a process that runs (or imports
        # and registers) the server. A REMOTE process that never
        # registers the aliased server still falls back to the
        # function-name source scan — see ROADMAP follow-on.
        flag = getattr(handler, "_rpc_idempotent", None)
        if flag is not None:
            prev = _IDEMPOTENCY.get(method)
            _IDEMPOTENCY[method] = flag if prev is None else (prev and flag)

    def register_all(self, obj: Any, prefix: str = ""):
        """Register every ``rpc_*`` coroutine method of obj."""
        for attr in dir(obj):
            if attr.startswith("rpc_"):
                self.register(prefix + attr[4:], getattr(obj, attr))

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        self._server = await asyncio.start_server(self._on_connect, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self):
        for conn in list(self.connections):
            conn.abort(ConnectionLost("server stopped"))
        if self._server:
            self._server.close()
            try:
                # 3.12 wait_closed blocks until every handler drains; our
                # handlers exit on the aborts above, but bound it anyway.
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except Exception:
                pass

    def _dispatch_server_frame(self, conn, kind, msg_id, method, payload):
        if kind in (RESPONSE, ERROR):
            conn._dispatch_response(kind, msg_id, payload)
            return
        handler = self._handlers.get(method)
        if handler is None:
            if kind == REQUEST:
                conn.send_nowait(ERROR, msg_id, method,
                                 (method, "KeyError",
                                  f"no handler {method}", ""))
            return
        delay = _injected_delay(method)
        if kind == REQUEST:
            asyncio.ensure_future(self._run_handler(
                conn, msg_id, method, handler, payload, delay))
        else:  # NOTIFY
            asyncio.ensure_future(self._run_notify(
                conn, method, handler, payload, delay))

    async def _on_connect(self, reader, writer):
        conn = Connection(reader, writer)
        self.connections.add(conn)
        conn.on_close = lambda c: self.connections.discard(c)
        try:
            while True:
                kind, msg_id, method, payload = await _read_msg(reader)
                if kind == BATCH:
                    # In-order dispatch: handlers are *scheduled* in frame
                    # order, same guarantee as per-frame delivery.
                    for sub in payload:
                        self._dispatch_server_frame(conn, *sub)
                else:
                    self._dispatch_server_frame(conn, kind, msg_id, method,
                                                payload)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except Exception:
            logger.exception("%s: connection loop error", self.name)
        finally:
            conn.abort(ConnectionLost("peer disconnected"))

    async def _run_handler(self, conn, msg_id, method, handler, payload,
                           delay: float = 0.0):
        try:
            if delay:
                await asyncio.sleep(delay)
            result = await handler(conn, payload)
            await conn.send(RESPONSE, msg_id, method, result)
        except ConnectionLost:
            pass
        except Exception as e:
            tb = traceback.format_exc()
            try:
                await conn.send(ERROR, msg_id, method,
                                (method, type(e).__name__, str(e), tb))
            except Exception:
                pass

    async def _run_notify(self, conn, method, handler, payload,
                          delay: float = 0.0):
        try:
            if delay:
                await asyncio.sleep(delay)
            await handler(conn, payload)
        except Exception:
            logger.exception("%s: notify handler %s failed", self.name, method)


async def connect(address: str, push_handler: Optional[Callable] = None,
                  timeout: float = 10.0) -> Connection:
    host, port = address.rsplit(":", 1)
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port)), timeout)
    except (OSError, asyncio.TimeoutError) as e:
        # Normalize socket-level dial failures (ConnectionRefused when the
        # peer died) into the RPC error hierarchy so call sites only need
        # to catch RpcError.
        raise ConnectionLost(f"connect to {address} failed: {e}")
    conn = Connection(reader, writer, push_handler)
    asyncio.ensure_future(conn.client_loop())
    return conn


def backoff_delays(base: float = 0.1, cap: float = 2.0, rng=None):
    """Infinite generator of reconnect delays: exponential growth capped at
    `cap`, each sample jittered over [0.5x, 1.5x] so a fleet of clients
    that lost the same peer at the same instant de-synchronizes."""
    rng = rng or random.random
    delay = base
    while True:
        yield delay * (0.5 + rng())
        delay = min(delay * 2.0, cap)


class ReconnectingConnection:
    """Client connection that redials the same address on loss.

    Used for the GCS channel (head fault tolerance): a restarted GCS comes
    back on the same address, clients re-dial, run `on_reconnect` (e.g.
    resubscribe), and retry the in-flight request once per successful dial.
    """

    def __init__(self, address: str, push_handler: Optional[Callable] = None,
                 on_reconnect: Optional[Callable] = None,
                 retry_window_s: float = 30.0):
        self.address = address
        self.push_handler = push_handler
        self.on_reconnect = on_reconnect
        self.retry_window_s = retry_window_s
        self._conn: Optional[Connection] = None
        self._closed = False
        self._dial_lock = asyncio.Lock()

    @property
    def closed(self) -> bool:
        return self._closed or self._conn is None or self._conn.closed

    async def connect(self):
        self._conn = await connect(self.address, self.push_handler)
        return self

    async def _redial(self):
        async with self._dial_lock:
            if self._closed:
                raise ConnectionLost("channel closed")
            if self._conn is not None and not self._conn.closed:
                return  # another caller already reconnected
            deadline = asyncio.get_running_loop().time() + self.retry_window_s
            delays = backoff_delays()
            while not self._closed:
                try:
                    conn = await connect(self.address, self.push_handler,
                                         timeout=2.0)
                    if self.on_reconnect is not None:
                        await self.on_reconnect(conn)
                    self._conn = conn
                    return
                except Exception as e:
                    if asyncio.get_running_loop().time() > deadline:
                        raise ConnectionLost(
                            f"reconnect to {self.address} failed: {e}")
                    # Exponential backoff with jitter: a dead GCS address
                    # must not be hammered by every client in lockstep for
                    # the whole retry window (thundering redials).
                    await asyncio.sleep(next(delays))
            raise ConnectionLost("channel closed")

    async def request(self, method: str, payload: Any = None,
                      timeout: Optional[float] = None) -> Any:
        """Request with redial-and-replay on loss (GCS restart liveness).

        Replay policy mirrors ClientPool.request: a request that provably
        never left this process (`ConnectionLost.sent` False) is always
        safe to replay, but one that may have REACHED the peer is
        replayed only if the method is not annotated non-idempotent — a
        GCS that executed e.g. register_job and then dropped the
        connection must not run it twice."""
        attempts = 3
        for attempt in range(attempts):
            if self._conn is None or self._conn.closed:
                await self._redial()
            try:
                return await self._conn.request(method, payload, timeout)
            except ConnectionLost as e:
                if self._closed or attempt == attempts - 1:
                    raise
                if getattr(e, "sent", True) \
                        and idempotency_of(method) is False:
                    raise

    async def notify(self, method: str, payload: Any = None):
        if self._conn is None or self._conn.closed:
            await self._redial()
        await self._conn.notify(method, payload)

    async def close(self):
        self._closed = True
        if self._conn is not None:
            self._conn.abort(ConnectionLost("closed"))


class ClientPool:
    """Connection pool keyed by address, with lazy (re)connection."""

    def __init__(self, push_handler: Optional[Callable] = None):
        self._conns: Dict[str, Connection] = {}
        self._locks: Dict[str, asyncio.Lock] = {}
        self._push_handler = push_handler

    async def get(self, address: str) -> Connection:
        conn = self._conns.get(address)
        if conn is not None and not conn.closed:
            return conn
        lock = self._locks.setdefault(address, asyncio.Lock())
        async with lock:
            conn = self._conns.get(address)
            if conn is not None and not conn.closed:
                return conn
            conn = await connect(address, self._push_handler)
            self._conns[address] = conn
            return conn

    async def request(self, address: str, method: str, payload: Any = None,
                      timeout: Optional[float] = None,
                      retry_once: bool = True) -> Any:
        """Request with idempotency-aware redial on connection loss.

        Retry policy per attempt that died with ConnectionLost:
        - the request never left this process (`sent` False): always safe
          to retry — invalidate the stale pooled connection and re-dial;
        - the request may have reached the peer (`sent` True): retry only
          if the method is NOT annotated non-idempotent (see
          idempotent()/non_idempotent(); replaying e.g. register_job on a
          live peer that merely dropped the connection double-executes);
        - methods annotated idempotent get one extra redial attempt — a
          peer restarting mid-redial no longer fails them.
        Callers with their own at-most-once accounting (task/actor
        pushes) pass retry_once=False and see the raw error.
        """
        attempts = None  # resolved on the FAILURE path only: the first
        attempt = 0      # unknown-method idempotency_of() may walk the
        while True:      # package source — never tax a healthy request.
            conn = await self.get(address)
            try:
                return await conn.request(method, payload, timeout)
            except ConnectionLost as e:
                if not retry_once:
                    raise
                if attempts is None:
                    attempts = 3 if idempotency_of(method) else 2
                attempt += 1
                if attempt >= attempts:
                    raise
                if getattr(e, "sent", True) \
                        and idempotency_of(method) is False:
                    raise
                # The pooled connection may be stale (peer restarted on
                # the same address): invalidate and re-dial. A dial
                # failure re-raises ConnectionLost — the peer is gone.
                self.invalidate(address)

    def invalidate(self, address: str):
        conn = self._conns.pop(address, None)
        if conn:
            conn.abort(ConnectionLost("invalidated"))

    async def close_all(self):
        for conn in self._conns.values():
            conn.abort(ConnectionLost("pool closed"))
        self._conns.clear()
