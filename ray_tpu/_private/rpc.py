"""Asyncio RPC layer: the control-plane transport for every daemon.

Plays the role of the reference's gRPC wrappers (src/ray/rpc/grpc_server.h,
grpc_client.h): request/response with correlation ids, one-way notifications,
and server->client pushes (used for pubsub long-poll equivalents). TCP with a
length-prefixed pickled envelope; payloads are plain Python structures.

Envelope: u32 length | pickle([kind, msg_id, method, payload])
    kind: 0=request 1=response 2=error-response 3=notify 4=push
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import pickle
import random
import struct
import traceback
from typing import Any, Awaitable, Callable, Dict, Optional

logger = logging.getLogger(__name__)

REQUEST, RESPONSE, ERROR, NOTIFY, PUSH = 0, 1, 2, 3, 4

_MAX_MSG = 1 << 31

# ---- deterministic race-shaking (reference: ray_config_def.h:838
# RAY_testing_asio_delay_us) ------------------------------------------------
# RAY_TPU_TESTING_RPC_DELAY_US="<method-glob>=<min_us>:<max_us>[,...]"
# delays the START of matching handlers by a uniform random amount, which
# also reorders concurrently-arriving messages — the asyncio analogue of
# running the C++ core under randomized asio delays.
_delay_spec: Optional[list] = None


def _load_delay_spec() -> list:
    import os
    spec = []
    raw = os.environ.get("RAY_TPU_TESTING_RPC_DELAY_US", "")
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        pat, _, rng = part.partition("=")
        lo, _, hi = rng.partition(":")
        try:
            spec.append((pat, int(lo), int(hi or lo)))
        except ValueError:
            logger.warning("bad RPC delay spec part %r", part)
    return spec


def _injected_delay(method: str) -> float:
    """Seconds of injected delay for this method (0.0 = none)."""
    global _delay_spec
    if _delay_spec is None:
        _delay_spec = _load_delay_spec()
    if not _delay_spec:
        return 0.0
    import fnmatch
    import random
    for pat, lo, hi in _delay_spec:
        if fnmatch.fnmatch(method, pat):
            return random.uniform(lo, hi) / 1e6
    return 0.0


class RpcError(Exception):
    pass


class RemoteRpcError(RpcError):
    def __init__(self, method: str, err_type: str, message: str, tb: str):
        self.method = method
        self.err_type = err_type
        self.err_message = message
        self.remote_traceback = tb
        super().__init__(f"RPC {method} failed remotely: {err_type}: {message}\n{tb}")

    def __reduce__(self):
        # Default Exception reduction would replay self.args (1 string) into
        # the 4-arg __init__ and break unpickling wherever this instance is
        # embedded (e.g. inside a serialized task error).
        return (RemoteRpcError, (self.method, self.err_type, self.err_message,
                                 self.remote_traceback))


class ConnectionLost(RpcError):
    pass


async def _read_msg(reader: asyncio.StreamReader):
    header = await reader.readexactly(4)
    (length,) = struct.unpack("<I", header)
    if length > _MAX_MSG:
        raise RpcError(f"message too large: {length}")
    data = await reader.readexactly(length)
    return pickle.loads(data)


def _encode(kind: int, msg_id: int, method: str, payload: Any) -> bytes:
    body = pickle.dumps([kind, msg_id, method, payload], protocol=5)
    return struct.pack("<I", len(body)) + body


class Connection:
    """One live duplex connection; shared by client and server sides."""

    _ids = itertools.count(1)

    HIGH_WATER = 1 << 20  # drain (backpressure) only past this buffer size

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 push_handler: Optional[Callable] = None):
        self.reader = reader
        self.writer = writer
        self.push_handler = push_handler
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self.on_close: Optional[Callable] = None
        # Set by server loop: peer-provided identity metadata.
        self.peer_info: dict = {}
        # Write coalescing: frames queued within one loop tick flush as a
        # single transport write (one syscall), see send_nowait.
        self._out: list = []
        self._out_bytes = 0
        self._flush_scheduled = False

    @property
    def closed(self) -> bool:
        return self._closed

    def send_nowait(self, kind: int, msg_id: int, method: str, payload: Any):
        """Send with adaptive coalescing: the first frame of a loop tick
        writes through immediately (no latency tax on serial
        request-reply), later frames of the same tick batch into one
        write (a burst of pipelined pushes/replies costs one socket.send
        — measured ~64 us per send syscall on this box, the dominant term
        of the round-2 task-throughput gap). Loop thread only.
        """
        if self._closed:
            raise ConnectionLost("connection closed")
        data = _encode(kind, msg_id, method, payload)
        if self._flush_scheduled:
            self._out.append(data)
            self._out_bytes += len(data)
            return
        self.writer.write(data)
        self._flush_scheduled = True
        asyncio.get_running_loop().call_soon(self._flush)

    def _flush(self):
        self._flush_scheduled = False
        if self._closed or not self._out:
            return
        data = self._out[0] if len(self._out) == 1 else b"".join(self._out)
        self._out.clear()
        self._out_bytes = 0
        self.writer.write(data)

    async def send(self, kind: int, msg_id: int, method: str, payload: Any):
        self.send_nowait(kind, msg_id, method, payload)
        transport = self.writer.transport
        if self._out_bytes > self.HIGH_WATER:
            self._flush()
        if (transport is not None
                and transport.get_write_buffer_size() > self.HIGH_WATER):
            await self.writer.drain()

    async def request(self, method: str, payload: Any = None,
                      timeout: Optional[float] = None) -> Any:
        msg_id = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        try:
            await self.send(REQUEST, msg_id, method, payload)
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(msg_id, None)

    async def notify(self, method: str, payload: Any = None):
        await self.send(NOTIFY, 0, method, payload)

    async def push(self, method: str, payload: Any = None):
        await self.send(PUSH, 0, method, payload)

    def abort(self, exc: Exception):
        if self._closed:
            return
        self._closed = True
        self._out.clear()
        self._out_bytes = 0
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(str(exc)))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close:
            try:
                self.on_close(self)
            except Exception:
                pass

    async def close(self):
        self.abort(ConnectionLost("closed"))

    async def _dispatch_response(self, kind, msg_id, payload):
        fut = self._pending.get(msg_id)
        if fut is None or fut.done():
            return
        if kind == RESPONSE:
            fut.set_result(payload)
        else:
            method, err_type, message, tb = payload
            fut.set_exception(RemoteRpcError(method, err_type, message, tb))

    async def client_loop(self):
        """Read loop for the client side of a connection."""
        try:
            while True:
                kind, msg_id, method, payload = await _read_msg(self.reader)
                if kind in (RESPONSE, ERROR):
                    await self._dispatch_response(kind, msg_id, payload)
                elif kind == PUSH and self.push_handler is not None:
                    try:
                        res = self.push_handler(method, payload)
                        if asyncio.iscoroutine(res):
                            asyncio.ensure_future(res)
                    except Exception:
                        logger.exception("push handler failed for %s", method)
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            self.abort(e)
        except Exception as e:
            logger.exception("client loop error")
            self.abort(e)


Handler = Callable[[Connection, Any], Awaitable[Any]]


class RpcServer:
    def __init__(self, name: str = "server"):
        self.name = name
        self._handlers: Dict[str, Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set[Connection] = set()
        self.port: int = 0

    def register(self, method: str, handler: Handler):
        self._handlers[method] = handler

    def register_all(self, obj: Any, prefix: str = ""):
        """Register every ``rpc_*`` coroutine method of obj."""
        for attr in dir(obj):
            if attr.startswith("rpc_"):
                self.register(prefix + attr[4:], getattr(obj, attr))

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        self._server = await asyncio.start_server(self._on_connect, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self):
        for conn in list(self.connections):
            conn.abort(ConnectionLost("server stopped"))
        if self._server:
            self._server.close()
            try:
                # 3.12 wait_closed blocks until every handler drains; our
                # handlers exit on the aborts above, but bound it anyway.
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except Exception:
                pass

    async def _on_connect(self, reader, writer):
        conn = Connection(reader, writer)
        self.connections.add(conn)
        conn.on_close = lambda c: self.connections.discard(c)
        try:
            while True:
                kind, msg_id, method, payload = await _read_msg(reader)
                if kind in (RESPONSE, ERROR):
                    await conn._dispatch_response(kind, msg_id, payload)
                    continue
                handler = self._handlers.get(method)
                if handler is None:
                    if kind == REQUEST:
                        await conn.send(ERROR, msg_id, method,
                                        (method, "KeyError", f"no handler {method}", ""))
                    continue
                delay = _injected_delay(method)
                if kind == REQUEST:
                    asyncio.ensure_future(self._run_handler(
                        conn, msg_id, method, handler, payload, delay))
                else:  # NOTIFY
                    asyncio.ensure_future(self._run_notify(
                        conn, method, handler, payload, delay))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except Exception:
            logger.exception("%s: connection loop error", self.name)
        finally:
            conn.abort(ConnectionLost("peer disconnected"))

    async def _run_handler(self, conn, msg_id, method, handler, payload,
                           delay: float = 0.0):
        try:
            if delay:
                await asyncio.sleep(delay)
            result = await handler(conn, payload)
            await conn.send(RESPONSE, msg_id, method, result)
        except ConnectionLost:
            pass
        except Exception as e:
            tb = traceback.format_exc()
            try:
                await conn.send(ERROR, msg_id, method,
                                (method, type(e).__name__, str(e), tb))
            except Exception:
                pass

    async def _run_notify(self, conn, method, handler, payload,
                          delay: float = 0.0):
        try:
            if delay:
                await asyncio.sleep(delay)
            await handler(conn, payload)
        except Exception:
            logger.exception("%s: notify handler %s failed", self.name, method)


async def connect(address: str, push_handler: Optional[Callable] = None,
                  timeout: float = 10.0) -> Connection:
    host, port = address.rsplit(":", 1)
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port)), timeout)
    except (OSError, asyncio.TimeoutError) as e:
        # Normalize socket-level dial failures (ConnectionRefused when the
        # peer died) into the RPC error hierarchy so call sites only need
        # to catch RpcError.
        raise ConnectionLost(f"connect to {address} failed: {e}")
    conn = Connection(reader, writer, push_handler)
    asyncio.ensure_future(conn.client_loop())
    return conn


def backoff_delays(base: float = 0.1, cap: float = 2.0, rng=None):
    """Infinite generator of reconnect delays: exponential growth capped at
    `cap`, each sample jittered over [0.5x, 1.5x] so a fleet of clients
    that lost the same peer at the same instant de-synchronizes."""
    rng = rng or random.random
    delay = base
    while True:
        yield delay * (0.5 + rng())
        delay = min(delay * 2.0, cap)


class ReconnectingConnection:
    """Client connection that redials the same address on loss.

    Used for the GCS channel (head fault tolerance): a restarted GCS comes
    back on the same address, clients re-dial, run `on_reconnect` (e.g.
    resubscribe), and retry the in-flight request once per successful dial.
    """

    def __init__(self, address: str, push_handler: Optional[Callable] = None,
                 on_reconnect: Optional[Callable] = None,
                 retry_window_s: float = 30.0):
        self.address = address
        self.push_handler = push_handler
        self.on_reconnect = on_reconnect
        self.retry_window_s = retry_window_s
        self._conn: Optional[Connection] = None
        self._closed = False
        self._dial_lock = asyncio.Lock()

    @property
    def closed(self) -> bool:
        return self._closed or self._conn is None or self._conn.closed

    async def connect(self):
        self._conn = await connect(self.address, self.push_handler)
        return self

    async def _redial(self):
        async with self._dial_lock:
            if self._closed:
                raise ConnectionLost("channel closed")
            if self._conn is not None and not self._conn.closed:
                return  # another caller already reconnected
            deadline = asyncio.get_running_loop().time() + self.retry_window_s
            delays = backoff_delays()
            while not self._closed:
                try:
                    conn = await connect(self.address, self.push_handler,
                                         timeout=2.0)
                    if self.on_reconnect is not None:
                        await self.on_reconnect(conn)
                    self._conn = conn
                    return
                except Exception as e:
                    if asyncio.get_running_loop().time() > deadline:
                        raise ConnectionLost(
                            f"reconnect to {self.address} failed: {e}")
                    # Exponential backoff with jitter: a dead GCS address
                    # must not be hammered by every client in lockstep for
                    # the whole retry window (thundering redials).
                    await asyncio.sleep(next(delays))
            raise ConnectionLost("channel closed")

    async def request(self, method: str, payload: Any = None,
                      timeout: Optional[float] = None) -> Any:
        for _attempt in range(2):
            if self._conn is None or self._conn.closed:
                await self._redial()
            try:
                return await self._conn.request(method, payload, timeout)
            except ConnectionLost:
                if self._closed:
                    raise
                continue
        await self._redial()
        return await self._conn.request(method, payload, timeout)

    async def notify(self, method: str, payload: Any = None):
        if self._conn is None or self._conn.closed:
            await self._redial()
        await self._conn.notify(method, payload)

    async def close(self):
        self._closed = True
        if self._conn is not None:
            self._conn.abort(ConnectionLost("closed"))


class ClientPool:
    """Connection pool keyed by address, with lazy (re)connection."""

    def __init__(self, push_handler: Optional[Callable] = None):
        self._conns: Dict[str, Connection] = {}
        self._locks: Dict[str, asyncio.Lock] = {}
        self._push_handler = push_handler

    async def get(self, address: str) -> Connection:
        conn = self._conns.get(address)
        if conn is not None and not conn.closed:
            return conn
        lock = self._locks.setdefault(address, asyncio.Lock())
        async with lock:
            conn = self._conns.get(address)
            if conn is not None and not conn.closed:
                return conn
            conn = await connect(address, self._push_handler)
            self._conns[address] = conn
            return conn

    async def request(self, address: str, method: str, payload: Any = None,
                      timeout: Optional[float] = None) -> Any:
        conn = await self.get(address)
        return await conn.request(method, payload, timeout)

    def invalidate(self, address: str):
        conn = self._conns.pop(address, None)
        if conn:
            conn.abort(ConnectionLost("invalidated"))

    async def close_all(self):
        for conn in self._conns.values():
            conn.abort(ConnectionLost("pool closed"))
        self._conns.clear()
