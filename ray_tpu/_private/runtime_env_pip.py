"""pip runtime environments: per-env virtualenvs for dependency isolation.

Reference parity: python/ray/_private/runtime_env/pip.py — a task/actor
declaring runtime_env={"pip": [...]} runs in a worker whose interpreter
lives in a dedicated virtualenv with those packages. The venv is built
with the stdlib `venv` module (inheriting site-packages so the base
framework deps stay importable) and populated by an injectable installer —
the default shells out to `<venv>/bin/python -m pip install`, which needs
network access at deploy time (the runtime gate); tests inject a recording
installer.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import subprocess
import sys
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)

# installer(venv_python: str, packages: List[str]) -> None
Installer = Callable[[str, List[str]], None]


def pip_spec_hash(packages: List[str]) -> str:
    canon = json.dumps(sorted(packages)).encode()
    return hashlib.sha1(canon).hexdigest()[:16]


def default_installer(venv_python: str, packages: List[str]) -> None:
    """Real installer: pip inside the venv (needs network/index access)."""
    cmd = [venv_python, "-m", "pip", "install", "--no-input", *packages]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"pip install failed ({proc.returncode}): "
            f"{proc.stderr[-2000:]}")


class PipEnvManager:
    """Content-addressed venv cache: one venv per sorted package list."""

    def __init__(self, cache_dir: str, installer: Optional[Installer] = None):
        self.cache_dir = cache_dir
        self.installer = installer or default_installer
        os.makedirs(cache_dir, exist_ok=True)

    def _venv_dir(self, spec_hash: str) -> str:
        return os.path.join(self.cache_dir, f"pip-{spec_hash}")

    @staticmethod
    def venv_python(venv_dir: str) -> str:
        return os.path.join(venv_dir, "bin", "python")

    def ensure(self, packages: List[str]) -> str:
        """Create-or-reuse the venv for `packages`; returns its python.

        The venv inherits system site-packages so ray_tpu/jax remain
        importable; the marker file is written only after a successful
        install, so a crashed build is rebuilt, not reused.
        """
        packages = list(packages)
        h = pip_spec_hash(packages)
        venv_dir = self._venv_dir(h)
        marker = os.path.join(venv_dir, ".ray_tpu_ready")
        py = self.venv_python(venv_dir)
        if os.path.exists(marker) and os.path.exists(py):
            return py
        # Cross-process build lock: a gang of workers starting the same
        # env concurrently must not clear each other's half-built venv
        # (reference pip plugin serializes builds the same way).
        import fcntl
        lock_path = os.path.join(self.cache_dir, f"pip-{h}.lock")
        with open(lock_path, "w") as lock_f:
            fcntl.flock(lock_f, fcntl.LOCK_EX)
            try:
                return self._build_locked(packages, h, venv_dir, marker, py)
            finally:
                fcntl.flock(lock_f, fcntl.LOCK_UN)

    def _build_locked(self, packages, h, venv_dir, marker, py):
        if os.path.exists(marker) and os.path.exists(py):
            return py  # another process built it while we waited
        import venv as venv_mod
        logger.info("building pip runtime env %s: %s", h, packages)
        venv_mod.EnvBuilder(
            system_site_packages=True, with_pip=False,
            clear=os.path.isdir(venv_dir), symlinks=True).create(venv_dir)
        # When the base interpreter is ITSELF a venv (common in container
        # images), system_site_packages resolves to the SYSTEM python's
        # site-packages, not the base venv's — the framework deps would
        # vanish. A .pth file inheriting the parent's site-packages fixes
        # it (reference pip plugin: "inherit base environment" path).
        ver = f"python{sys.version_info[0]}.{sys.version_info[1]}"
        sp = os.path.join(venv_dir, "lib", ver, "site-packages")
        if os.path.isdir(sp):
            parents = [p for p in sys.path
                       if p.endswith("site-packages") and os.path.isdir(p)]
            with open(os.path.join(sp, "_ray_tpu_inherit.pth"), "w") as f:
                f.write("\n".join(parents) + "\n")
        if packages:
            self.installer(py, packages)
        with open(marker, "w") as f:
            json.dump({"packages": packages,
                       "base_python": sys.executable}, f)
        return py
