"""Node bootstrap: assembles GCS + raylet (+ session dir) for a head or worker
node (reference: python/ray/_private/node.py, services.py).

The default topology for `init()` runs the GCS and the head raylet on the
driver's background event loop (real TCP servers, so workers and other nodes
connect identically); `cluster_utils.Cluster` adds more raylets on the same
loop to emulate multi-node clusters in one process, mirroring the reference's
`ray.cluster_utils.Cluster` test harness.
"""

from __future__ import annotations

import asyncio
import os
import time
import uuid
from typing import Dict, Optional

from ray_tpu._private.config import Config
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.raylet import Raylet


def new_session_dir(config: Config) -> str:
    stamp = time.strftime("%Y%m%d-%H%M%S")
    path = os.path.join(config.session_dir_root,
                        f"session_{stamp}_{os.getpid()}_{uuid.uuid4().hex[:6]}")
    os.makedirs(os.path.join(path, "logs"), exist_ok=True)
    return path


class HeadNode:
    """GCS + head raylet living on the current asyncio loop."""

    def __init__(self, config: Config,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: Optional[int] = None,
                 session_dir: str = ""):
        self.config = config
        self.session_dir = session_dir or new_session_dir(config)
        self.gcs = GcsServer(config, self.session_dir)
        self.raylet: Optional[Raylet] = None
        # Optional ray_tpu:// proxy (util/client); owned by this node's
        # lifecycle when attached (cli --client-server-port).
        self.client_server = None
        self._resources = resources
        self._labels = labels
        self._object_store_memory = object_store_memory

    async def start(self, port: int = 0) -> str:
        gcs_address = await self.gcs.start(port=port)
        self.raylet = Raylet(
            self.config, gcs_address, self.session_dir,
            resources=self._resources, labels=self._labels, is_head=True,
            object_store_memory=self._object_store_memory, node_name="head")
        await self.raylet.start()
        return gcs_address

    async def stop(self):
        if self.client_server is not None:
            try:
                await self.client_server.stop()
            except Exception:
                pass
        if self.raylet:
            await self.raylet.stop()
        await self.gcs.stop()


def detect_node_resources(num_cpus: Optional[float] = None,
                          num_tpus: Optional[float] = None,
                          resources: Optional[Dict[str, float]] = None,
                          config: Optional[Config] = None) -> Dict[str, float]:
    """Auto-detect CPU/TPU/memory resources (reference:
    python/ray/_private/accelerators/tpu.py for TPU counting)."""
    res: Dict[str, float] = dict(resources or {})
    res.setdefault("CPU", float(num_cpus if num_cpus is not None
                                else (os.cpu_count() or 1)))
    if num_tpus is not None:
        res.setdefault("TPU", float(num_tpus))
    else:
        ntpu = _detect_tpu_chips()
        if ntpu:
            res.setdefault("TPU", float(ntpu))
    try:
        import psutil
        res.setdefault("memory", float(psutil.virtual_memory().available))
    except Exception:
        res.setdefault("memory", 8 * 1024**3)
    cfg = config or Config.load()
    res.setdefault("object_store_memory", float(cfg.object_store_memory))
    return res


def _detect_tpu_chips() -> int:
    """Count local TPU chips without initializing a JAX backend.

    Mirrors TPUAcceleratorManager.get_current_node_num_accelerators
    (reference python/ray/_private/accelerators/tpu.py:75): check
    TPU_VISIBLE_CHIPS / vfio device nodes, not jax (importing jax grabs
    the chip).
    """
    vis = os.environ.get("TPU_VISIBLE_CHIPS")
    if vis:
        return len([c for c in vis.split(",") if c.strip()])
    try:
        # TPU VMs expose one vfio device per chip.
        entries = os.listdir("/dev/vfio")
        chips = [e for e in entries if e.isdigit()]
        if chips:
            return len(chips)
    except OSError:
        pass
    if os.environ.get("RAY_TPU_FAKE_TPU_CHIPS"):
        return int(os.environ["RAY_TPU_FAKE_TPU_CHIPS"])
    # Under the axon tunnel there is one attached chip; detect via env.
    if os.environ.get("JAX_PLATFORMS", "").startswith(("axon", "tpu")):
        return 1
    return 0
