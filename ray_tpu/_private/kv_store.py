"""Standalone external KV store — the Redis-equivalent for GCS state.

Reference parity: the reference GCS persists its tables to an external
Redis so a restarted head (possibly on another machine) can recover
cluster state (src/ray/gcs/store_client/redis_store_client.h,
python/ray/_private/gcs_utils.py). Here the external store is a tiny
asyncio RPC server speaking the framework's own framed protocol
(`_private/rpc.py`), with per-key files on disk so the store itself
survives restarts.

Run it standalone:  python -m ray_tpu kv-store --port 6379 --dir /data
Point the head at it:  RAY_TPU_GCS_STORAGE_ADDRESS=host:6379 ray_tpu start --head
"""

from __future__ import annotations

import asyncio
import base64
import logging
import os
from typing import Dict, Optional

from ray_tpu._private import rpc

logger = logging.getLogger(__name__)


def _key_path(root: str, key: str) -> str:
    # Collision-free filename encoding (ADVICE r4: lossy sanitization
    # mapped distinct keys like 'a:b' and 'a_b' onto the same file, so one
    # persisted value silently clobbered the other). Long keys hash to a
    # fixed-width digest — base64 inflates 4/3 and would hit the 255-byte
    # filename limit for keys the old scheme persisted fine; the filename
    # need not be reversible (the real key is stored inside the file).
    kb = key.encode()
    name = base64.urlsafe_b64encode(kb).decode().rstrip("=")
    if len(name) > 180:
        import hashlib
        name = "h_" + hashlib.sha256(kb).hexdigest()
    return os.path.join(root, name + ".kv")


class KVStoreServer:
    """Blob store: set/get/delete/keys, everything persisted to disk.

    Values are opaque bytes. Writes are atomic (tmp + rename) so a
    concurrent reader or a crash mid-write never sees a torn value.
    """

    def __init__(self, data_dir: str = ""):
        self.data_dir = data_dir
        self.data: Dict[str, bytes] = {}
        self.server = rpc.RpcServer()
        self.address = ""
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._load()

    def _load(self):
        legacy: list = []
        for name in os.listdir(self.data_dir):
            if not name.endswith(".kv"):
                continue
            path = os.path.join(self.data_dir, name)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
                # first line = original key (files use an encoded name)
                nl = blob.index(b"\n")
                key = blob[:nl].decode()
            except (OSError, ValueError, UnicodeDecodeError) as e:
                # A malformed/truncated file must not abort store startup
                # (ADVICE r4): skip it with a warning and keep serving the
                # rest of the persisted state.
                logger.warning("kv-store: skipping malformed file %s (%s)",
                               path, e)
                continue
            if _key_path(self.data_dir, key) != path:
                # Pre-upgrade sanitized filename: queue for migration so a
                # stale old-named file can't clobber or resurrect the
                # current-encoding value on a later restart.
                legacy.append((key, blob[nl + 1:], path))
                continue
            self.data[key] = blob[nl + 1:]
        for key, value, old_path in legacy:
            if key not in self.data:  # current-encoding file wins
                self.data[key] = value
                self._persist(key, value)
            try:
                os.remove(old_path)
            except OSError:
                pass
            logger.info("kv-store: migrated legacy file %s", old_path)
        if self.data:
            logger.info("kv-store loaded %d keys from %s",
                        len(self.data), self.data_dir)

    def _persist(self, key: str, value: Optional[bytes]):
        if not self.data_dir:
            return
        path = _key_path(self.data_dir, key)
        if value is None:
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
            return
        tmp = path + ".tmp"
        # ray-tpu: noqa(ASYNC-BLOCK): write-through durability; the ack must follow this atomic one-key tmp+replace write
        with open(tmp, "wb") as f:
            f.write(key.encode() + b"\n" + value)
        os.replace(tmp, path)

    # ------------- RPC handlers -------------
    # Wire names are kv_store_* (not store_*): the raylet's object-store
    # service exposes a non-idempotent `store_get` (pins), and the
    # registry's safer-flag merge on a name collision would strip these
    # pure reads of their replay — the GCS external-store restore read
    # must survive a transient connection loss.

    @rpc.idempotent
    async def rpc_kv_store_set(self, conn, payload) -> dict:
        key, value = payload["key"], payload["value"]
        self.data[key] = value
        self._persist(key, value)
        return {"ok": True}

    @rpc.idempotent
    async def rpc_kv_store_get(self, conn, payload) -> dict:
        return {"value": self.data.get(payload["key"])}

    @rpc.idempotent
    async def rpc_kv_store_del(self, conn, payload) -> dict:
        existed = self.data.pop(payload["key"], None) is not None
        if existed:
            self._persist(payload["key"], None)
        return {"deleted": existed}

    @rpc.idempotent
    async def rpc_kv_store_keys(self, conn, payload) -> dict:
        prefix = payload.get("prefix", "")
        return {"keys": [k for k in self.data if k.startswith(prefix)]}

    @rpc.idempotent
    async def rpc_kv_store_ping(self, conn, payload) -> dict:
        return {"ok": True, "keys": len(self.data)}

    # ------------- lifecycle -------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self.server.register_all(self)
        actual = await self.server.start(host, port)
        self.address = f"{host}:{actual}"
        logger.info("kv-store listening at %s (dir=%s)",
                    self.address, self.data_dir or "<memory>")
        return self.address

    async def stop(self):
        await self.server.stop()


class ExternalStoreClient:
    """Async client the GCS uses to push/pull its snapshot blob."""

    def __init__(self, address: str, pool: Optional[rpc.ClientPool] = None):
        self.address = address
        self._pool = pool or rpc.ClientPool()
        self._own_pool = pool is None

    async def set(self, key: str, value: bytes):
        await self._pool.request(self.address, "kv_store_set",
                                 {"key": key, "value": value}, timeout=30)

    async def get(self, key: str) -> Optional[bytes]:
        out = await self._pool.request(self.address, "kv_store_get",
                                       {"key": key}, timeout=30)
        return out["value"]

    async def delete(self, key: str):
        await self._pool.request(self.address, "kv_store_del", {"key": key},
                                 timeout=30)

    async def ping(self) -> dict:
        return await self._pool.request(self.address, "kv_store_ping", {},
                                        timeout=10)

    async def close(self):
        if self._own_pool:
            await self._pool.close_all()


def run_server(host: str, port: int, data_dir: str):
    """Blocking entry point for `python -m ray_tpu kv-store`."""

    async def main():
        srv = KVStoreServer(data_dir)
        addr = await srv.start(host, port)
        print(f"ray_tpu kv-store running at {addr}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(main())
