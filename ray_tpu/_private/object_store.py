"""Shared-memory object store (plasma equivalent).

Capability parity with the reference plasma store (src/ray/object_manager/plasma/
store.h, object_lifecycle_manager.h, eviction_policy.h): a per-node arena of
shared memory managed by the node daemon; same-node workers attach to the
segment and read objects zero-copy; LRU eviction of unpinned objects with
fallback spilling to disk; create/seal lifecycle; pinning while mapped.

Differences from the reference (deliberate, TPU-first): a pool of mmap'd
segments with Python free-list allocators instead of dlmalloc (the pool grows
geometrically up to the configured capacity; the C++ arena allocator is a
planned drop-in via ctypes); client<->store protocol rides the framework
RPC layer instead of a bespoke flatbuffers unix-socket protocol.

This module is the node-local OBJECT PLANE: everything above the inline
threshold — core put/get, serve bodies, streaming-ingest blocks, podracer
weight broadcasts, compiled-DAG store channels — moves through these
segments, with spill-to-external-storage and chunked cross-node transfer as
the overflow paths (see ray_tpu/_private/object_plane.py for the facade).
"""

from __future__ import annotations

import asyncio
import logging
import mmap as mmap_mod
import os
import threading
import time
from collections import OrderedDict
from multiprocessing import shared_memory, resource_tracker
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_ALIGN = 64


class _AttachedSegment:
    """Read-write attach to an existing shm segment WITHOUT touching Python's
    resource tracker.

    SharedMemory(name=...) in 3.12 registers the segment with the (shared)
    tracker even on attach; unregistering from this process then removes the
    CREATOR's registration too, so the creator's clean unlink at exit makes
    the tracker print a KeyError. mmap'ing /dev/shm directly sidesteps the
    tracker; only the creating node daemon owns the segment's lifetime.
    """

    __slots__ = ("name", "_file", "_mmap", "buf")

    def __init__(self, name: str):
        import mmap

        self.name = name
        self._file = open(f"/dev/shm/{name}", "r+b")
        size = os.fstat(self._file.fileno()).st_size
        self._mmap = mmap.mmap(self._file.fileno(), size)
        self.buf = memoryview(self._mmap)

    def close(self):
        self.buf.release()
        self._mmap.close()
        self._file.close()


def _attach_untracked(name: str):
    if os.path.exists(f"/dev/shm/{name}"):
        return _AttachedSegment(name)
    # Non-Linux fallback: tracked attach + best-effort unregister.
    shm = shared_memory.SharedMemory(name=name)
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:
        pass
    return shm


class _CreatedSegment:
    """Creator-side segment without Python's resource tracker.

    SharedMemory(create=True) spawns a resource-tracker helper process
    which (observed on this box) spins ~15% of a core after our workers
    fork — a flat tax on every put. The store daemon owns the segment's
    lifetime explicitly, so the tracker buys nothing: create the /dev/shm
    file directly and unlink it on destroy.
    """

    __slots__ = ("name", "_fd", "_mmap", "buf")

    def __init__(self, name: str, size: int):
        import mmap

        self.name = name
        self._fd = os.open(f"/dev/shm/{name}",
                           os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        os.ftruncate(self._fd, size)
        self._mmap = mmap.mmap(self._fd, size)
        self.buf = memoryview(self._mmap)

    def close(self):
        self.buf.release()
        self._mmap.close()
        os.close(self._fd)

    def unlink(self):
        try:
            os.unlink(f"/dev/shm/{self.name}")
        except OSError:
            pass


class Arena:
    """First-fit free-list allocator over one shared-memory segment."""

    def __init__(self, capacity: int, name_prefix: str = "rtpu"):
        self.capacity = capacity
        name = f"{name_prefix}_{os.getpid()}_{os.urandom(4).hex()}"
        if os.path.isdir("/dev/shm"):
            self.shm = _CreatedSegment(name, capacity)
        else:  # non-Linux fallback: tracked create
            self.shm = shared_memory.SharedMemory(create=True, size=capacity,
                                                  name=name)
        self.name = self.shm.name
        # free list: sorted list of (offset, size). Only touched from the
        # store's event-loop thread (the page warmer needs no allocator
        # coordination — madvise populates pages without modifying data).
        self._free: List[Tuple[int, int]] = [(0, capacity)]
        self.used = 0

    def alloc(self, size: int) -> Optional[int]:
        size = (size + _ALIGN - 1) // _ALIGN * _ALIGN
        for i, (off, sz) in enumerate(self._free):
            if sz >= size:
                if sz == size:
                    self._free.pop(i)
                else:
                    self._free[i] = (off + size, sz - size)
                self.used += size
                return off
        return None

    def free(self, offset: int, size: int):
        size = (size + _ALIGN - 1) // _ALIGN * _ALIGN
        self.used -= size
        # insert and coalesce
        self._free.append((offset, size))
        self._free.sort()
        merged: List[Tuple[int, int]] = []
        for off, sz in self._free:
            if merged and merged[-1][0] + merged[-1][1] == off:
                merged[-1] = (merged[-1][0], merged[-1][1] + sz)
            else:
                merged.append((off, sz))
        self._free = merged

    def view(self, offset: int, size: int) -> memoryview:
        return memoryview(self.shm.buf)[offset : offset + size]

    def destroy(self):
        try:
            self.shm.close()
        except Exception:
            # Zero-copy views may still pin the buffer (in-process driver);
            # the mapping leaks until process exit but the file must not.
            pass
        try:
            self.shm.unlink()
        except Exception:
            pass


class SegmentPool:
    """Multi-segment arena: the node-local object plane's memory.

    One logical capacity backed by several mmap'd segments. The pool
    starts with one segment and GROWS — geometric doubling, clamped to
    the logical capacity — when an allocation does not fit the existing
    segments. Growth instead of one giant up-front segment keeps small
    clusters (fake multi-node tests run several stores per process)
    from reserving gigabytes each, while a real node still reaches full
    capacity under load. Segments are append-only: once created they
    live until destroy() (clients cache attachments by segment name, so
    recycling a name would alias stale mappings).
    """

    _INITIAL_SEGMENT = 256 << 20

    def __init__(self, capacity: int, name_prefix: str = "rtpu",
                 initial_segment: Optional[int] = None,
                 on_segment_created=None):
        self.capacity = capacity
        self._name_prefix = name_prefix
        self._on_segment_created = on_segment_created
        self.segments: List[Arena] = []
        self._by_name: Dict[str, Arena] = {}
        first = min(capacity, initial_segment or self._INITIAL_SEGMENT)
        self._add_segment(first)

    @property
    def allocated(self) -> int:
        """Bytes of shm actually reserved (sum of segment sizes)."""
        return sum(s.capacity for s in self.segments)

    @property
    def used(self) -> int:
        return sum(s.used for s in self.segments)

    def _add_segment(self, size: int) -> Arena:
        seg = Arena(size, name_prefix=self._name_prefix)
        self.segments.append(seg)
        self._by_name[seg.name] = seg
        if self._on_segment_created is not None:
            self._on_segment_created(seg)
        return seg

    def alloc(self, size: int) -> Optional[Tuple[str, int]]:
        """Returns (segment_name, offset) or None when full even after
        growing to capacity."""
        for seg in self.segments:
            off = seg.alloc(size)
            if off is not None:
                return seg.name, off
        # Grow: double the last segment size (at least `size`), clamped
        # to what logical capacity remains.
        headroom = self.capacity - self.allocated
        if headroom <= 0:
            return None
        aligned = (size + _ALIGN - 1) // _ALIGN * _ALIGN
        want = max(self.segments[-1].capacity * 2 if self.segments else 0,
                   aligned, self._INITIAL_SEGMENT)
        grow = min(headroom, want)
        if grow < aligned:
            return None
        seg = self._add_segment(grow)
        off = seg.alloc(size)
        if off is None:
            return None
        return seg.name, off

    def free(self, name: str, offset: int, size: int):
        seg = self._by_name.get(name)
        if seg is not None:
            seg.free(offset, size)

    def view(self, name: str, offset: int, size: int) -> memoryview:
        return self._by_name[name].view(offset, size)

    def destroy(self):
        for seg in self.segments:
            seg.destroy()
        self.segments.clear()
        self._by_name.clear()


CREATING, SEALED, SPILLED = 0, 1, 2


class ObjectEntry:
    __slots__ = ("object_id", "segment", "offset", "size", "state", "pins",
                 "metadata", "owner_address", "spill_path", "create_time",
                 "delete_on_unpin")

    def __init__(self, object_id: bytes, segment: str, offset: int, size: int,
                 metadata: bytes = b"", owner_address: str = ""):
        self.object_id = object_id
        self.segment = segment
        self.offset = offset
        self.size = size
        self.state = CREATING
        self.pins = 0
        self.metadata = metadata
        self.owner_address = owner_address
        self.spill_path = ""
        self.create_time = time.time()
        self.delete_on_unpin = False


class ObjectStoreHost:
    """Runs inside the node daemon; owns the arena and the object index."""

    def __init__(self, capacity: int, spill_dir: str, prefault: bool = True,
                 initial_segment: Optional[int] = None):
        self._prefault = prefault
        self._prefault_budget = self._PREFAULT_CAP
        self._prefault_stops: List[threading.Event] = []
        self.pool = SegmentPool(capacity, initial_segment=initial_segment,
                                on_segment_created=self._segment_created)
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        # Spill backend: local disk by default, or an external store
        # (s3://...) via RAY_TPU_SPILL_STORAGE_URI (reference:
        # _private/external_storage.py S3-class spill URIs).
        from ray_tpu._private.external_storage import storage_from_uri
        self.spill_storage = storage_from_uri(
            os.environ.get("RAY_TPU_SPILL_STORAGE_URI", ""), spill_dir)
        self.objects: Dict[bytes, ObjectEntry] = {}
        # LRU over sealed, unpinned objects (insertion-ordered).
        self._lru: OrderedDict[bytes, None] = OrderedDict()
        self._seal_waiters: Dict[bytes, List[asyncio.Future]] = {}
        self.num_spilled = 0
        self.num_evicted = 0
        self.bytes_spilled = 0
        # Object-plane observability (exported as gauges/counters by the
        # raylet metrics loop; see README metrics catalog).
        self.pinned_bytes = 0
        self.num_hits = 0
        self.num_misses = 0
        self.num_zero_copy_gets = 0

    _PREFAULT_CAP = 1 << 30
    _PREFAULT_CHUNK = 32 << 20

    def _segment_created(self, seg: Arena):
        if self._prefault:
            self._start_prefault(seg)

    def _start_prefault(self, seg: Arena):
        """Warm arena pages in a background thread so first writes into
        fresh regions run at warm-memcpy speed (~8 GB/s on this VM class)
        instead of hypervisor-fault speed (~0.1 GB/s) — the round-1
        put-throughput killer.

        posix_fallocate is NOT sufficient here: on a memory-ballooned VM it
        reserves tmpfs blocks without faulting the backing pages (measured:
        writes after fallocate still run at cold speed). The warmer uses
        madvise(MADV_POPULATE_WRITE) in chunks: it faults pages in WITHOUT
        modifying data, so it is race-free with concurrent object writes
        and needs no allocator coordination. MADV_WILLNEED over the whole
        arena first is free and lifts unwarmed-region writes ~6x on its
        own. Short sleeps keep the warmer off the critical path on small
        boxes; free-list reuse keeps regions warm afterwards.

        Runs once per segment: a pool that grows under load warms each
        new segment as it appears, drawing from one shared budget so a
        multi-segment store never populates more than _PREFAULT_CAP
        (or an eighth of MemAvailable) in total."""
        mm = getattr(seg.shm, "_mmap", None)
        if mm is None:
            return
        # POPULATE makes pages physically resident, so cap by the box's
        # available memory (an 8th) as well as the absolute cap — a fake
        # multi-node test cluster runs several stores in one process.
        avail = None
        try:
            with open("/proc/meminfo") as f:
                for ln in f:
                    if ln.startswith("MemAvailable:"):
                        avail = int(ln.split()[1]) * 1024
                        break
        except OSError:
            pass
        n = min(seg.capacity, self._prefault_budget,
                *( [avail // 8] if avail else [] ))
        if n <= 0:
            return
        self._prefault_budget -= n
        stop = threading.Event()
        self._prefault_stops.append(stop)
        chunk = self._PREFAULT_CHUNK
        MADV_POPULATE_WRITE = 23  # Linux 5.14+

        def _populate():
            try:
                mm.madvise(mmap_mod.MADV_WILLNEED)
            except (OSError, ValueError):
                pass
            for base in range(0, n, chunk):
                if stop.is_set():
                    return
                try:
                    mm.madvise(MADV_POPULATE_WRITE, base,
                               min(chunk, n - base))
                except (OSError, ValueError):
                    return  # pre-5.14 kernel: WILLNEED already applied
                time.sleep(0.02)

        threading.Thread(target=_populate, daemon=True,
                         name="store-prefault").start()

    # ---- lifecycle ----

    def create(self, object_id: bytes, size: int, metadata: bytes = b"",
               owner_address: str = "") -> Tuple[str, int]:
        if object_id in self.objects:
            ent = self.objects[object_id]
            if ent.state == SPILLED:
                # Re-creating a spilled object (e.g. restore): drop spill copy.
                self._delete_spill(ent)
                del self.objects[object_id]
            else:
                raise ValueError(f"object {object_id.hex()} already exists")
        loc = self.pool.alloc(size)
        if loc is None:
            self._make_room(size)
            loc = self.pool.alloc(size)
        if loc is None:
            raise MemoryError(
                f"object store full: need {size}, capacity {self.pool.capacity}")
        name, offset = loc
        ent = ObjectEntry(object_id, name, offset, size, metadata,
                          owner_address)
        self.objects[object_id] = ent
        return name, offset

    def seal(self, object_id: bytes):
        ent = self.objects[object_id]
        ent.state = SEALED
        if ent.pins == 0:
            self._lru[object_id] = None
        for fut in self._seal_waiters.pop(object_id, []):
            if not fut.done():
                fut.set_result(True)

    def write_and_seal(self, object_id: bytes, data, metadata: bytes = b"",
                       owner_address: str = ""):
        """Host-side put (used by object transfer and spill restore).

        Keyed upsert: a put for an object that already exists SEALED is a
        no-op success, not an error — object content is immutable per id,
        so the bytes are identical by construction. This is what makes
        `store_put_bytes` honestly @rpc.idempotent: a replayed transfer
        whose first attempt landed (reply lost with the connection) must
        report success, or drain push-off would count a completed
        migration as failed and skip telling the owner the new location."""
        ent = self.objects.get(object_id)
        if ent is not None and ent.state == SEALED:
            return
        name, offset = self.create(object_id, len(data), metadata, owner_address)
        self.pool.view(name, offset, len(data))[:] = data
        self.seal(object_id)

    def contains(self, object_id: bytes) -> bool:
        ent = self.objects.get(object_id)
        return ent is not None and ent.state in (SEALED, SPILLED)

    def pin(self, object_id: bytes) -> Optional[Tuple[str, int, int, bytes]]:
        """Pin + describe a sealed object; restores from spill if needed.

        Returns (segment_name, offset, size, metadata) or None if absent.
        """
        ent = self.objects.get(object_id)
        if ent is None or ent.state == CREATING:
            self.num_misses += 1
            return None
        if ent.state == SPILLED:
            self._restore(ent)
        if ent.pins == 0:
            self.pinned_bytes += ent.size
        ent.pins += 1
        self.num_hits += 1
        self._lru.pop(object_id, None)
        return ent.segment, ent.offset, ent.size, ent.metadata

    def unpin(self, object_id: bytes):
        ent = self.objects.get(object_id)
        if ent is None:
            return
        if ent.pins > 0:
            ent.pins -= 1
            if ent.pins == 0:
                self.pinned_bytes -= ent.size
        if ent.pins == 0:
            if ent.delete_on_unpin:
                self.delete(object_id)
            elif ent.state == SEALED:
                self._lru[object_id] = None

    def delete(self, object_id: bytes):
        ent = self.objects.get(object_id)
        if ent is None:
            return
        if ent.pins > 0:
            # A reader holds a zero-copy view into this region; defer the
            # free until the last unpin (reference: plasma delete semantics).
            ent.delete_on_unpin = True
            return
        self.objects.pop(object_id, None)
        self._lru.pop(object_id, None)
        if ent.state == SPILLED:
            self._delete_spill(ent)
        else:
            self.pool.free(ent.segment, ent.offset, ent.size)

    def abort_create(self, object_id: bytes):
        """Roll back a CREATING entry after a failed write/transfer (or a
        writer that died between create and seal — the raylet calls this
        for every CREATING object a disconnecting client left behind)."""
        ent = self.objects.get(object_id)
        if ent is None or ent.state != CREATING:
            return
        self.objects.pop(object_id, None)
        self.pool.free(ent.segment, ent.offset, ent.size)

    async def wait_sealed(self, object_id: bytes, timeout: Optional[float] = None) -> bool:
        ent = self.objects.get(object_id)
        if ent is not None and ent.state in (SEALED, SPILLED):
            return True
        fut = asyncio.get_running_loop().create_future()
        self._seal_waiters.setdefault(object_id, []).append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def read_bytes(self, object_id: bytes) -> Optional[bytes]:
        """Copy out an object's bytes (for transfer/spill); pins during read."""
        desc = self.pin(object_id)
        if desc is None:
            return None
        try:
            name, offset, size, _ = desc
            return bytes(self.pool.view(name, offset, size))
        finally:
            self.unpin(object_id)

    def view(self, segment: str, offset: int, size: int) -> memoryview:
        """Zero-copy view into a segment; caller must hold a pin."""
        return self.pool.view(segment, offset, size)

    # ---- eviction & spilling ----

    def _make_room(self, size: int):
        """Spill LRU unpinned objects until `size` fits."""
        target = size
        victims = list(self._lru.keys())
        for oid in victims:
            if self.pool.capacity - self.pool.used >= target:
                break
            ent = self.objects.get(oid)
            if ent is None or ent.pins > 0 or ent.state != SEALED:
                continue
            self._spill(ent)
        # Note: fragmentation may still prevent the alloc; caller re-tries.

    def _spill(self, ent: ObjectEntry):
        ent.spill_path = self.spill_storage.put(
            ent.object_id.hex(),
            self.pool.view(ent.segment, ent.offset, ent.size))
        self.pool.free(ent.segment, ent.offset, ent.size)
        ent.state = SPILLED
        self._lru.pop(ent.object_id, None)
        self.num_spilled += 1
        self.bytes_spilled += ent.size
        logger.debug("spilled object %s (%d bytes)", ent.object_id.hex()[:12], ent.size)

    def _restore(self, ent: ObjectEntry):
        data = self.spill_storage.get(ent.spill_path)
        loc = self.pool.alloc(len(data))
        if loc is None:
            self._make_room(len(data))
            loc = self.pool.alloc(len(data))
        if loc is None:
            raise MemoryError("cannot restore spilled object: store full")
        name, offset = loc
        self.pool.view(name, offset, len(data))[:] = data
        self._delete_spill(ent)
        ent.segment, ent.offset, ent.size, ent.state = \
            name, offset, len(data), SEALED

    def _delete_spill(self, ent: ObjectEntry):
        self.spill_storage.delete(ent.spill_path)
        ent.spill_path = ""

    def stats(self) -> dict:
        return {
            "capacity": self.pool.capacity,
            "allocated": self.pool.allocated,
            "used": self.pool.used,
            "num_segments": len(self.pool.segments),
            "num_objects": len(self.objects),
            "num_spilled": self.num_spilled,
            "bytes_spilled": self.bytes_spilled,
            "pinned_bytes": self.pinned_bytes,
            "num_hits": self.num_hits,
            "num_misses": self.num_misses,
            "num_zero_copy_gets": self.num_zero_copy_gets,
        }

    def destroy(self):
        for stop in self._prefault_stops:
            stop.set()
        self.pool.destroy()


class ObjectStoreClient:
    """Same-node client: attaches the daemon's segment for zero-copy reads.

    All control ops go over the node-daemon RPC connection supplied by the
    caller; data moves through shared memory only.
    """

    def __init__(self, request_fn, notify_fn=None):
        """request_fn: async (method, payload) -> result, bound to the raylet.
        notify_fn: optional async one-way (method, payload) on the same
        ordered connection; used for seal (no reply needed — readers racing
        an in-flight seal fall into the store's wait_sealed path)."""
        self._request = request_fn
        self._notify = notify_fn
        self._segments: Dict[str, shared_memory.SharedMemory] = {}

    def _segment(self, name: str) -> shared_memory.SharedMemory:
        shm = self._segments.get(name)
        if shm is None:
            shm = _attach_untracked(name)
            self._segments[name] = shm
        return shm

    async def put(self, object_id: bytes, serialized, metadata: bytes = b"",
                  owner_address: str = ""):
        """serialized: SerializedObject — written directly into shm."""
        size = serialized.total_size
        name, offset = await self._request(
            "store_create",
            {"object_id": object_id, "size": size, "metadata": metadata,
             "owner_address": owner_address},
        )
        shm = self._segment(name)
        try:
            if size > (4 << 20):
                # Big write: off-loop so the event loop stays responsive,
                # via a plain memcpy through the shared mapping. On this VM
                # class, WARM tmpfs pages memcpy at ~8.4 GB/s through the
                # mapping vs ~3.3 GB/s through pwrite (syscall + page-cache
                # path); COLD (never-touched) pages are hypervisor-fault-
                # bound at ~0.1 GB/s either way, and the store warms each
                # segment in the background (ObjectStoreHost._start_prefault)
                # so steady-state puts land on warm pages.
                dest = memoryview(shm.buf)[offset : offset + size]
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, serialized.write_to, dest)
            else:
                dest = memoryview(shm.buf)[offset : offset + size]
                serialized.write_to(dest)
        except BaseException:
            # The entry is CREATING and would otherwise wedge readers in
            # wait_sealed while leaking its region; roll it back.
            try:
                await self._request("store_abort", {"object_id": object_id})
            except Exception:
                pass
            raise
        if self._notify is not None:
            await self._notify("store_seal", {"object_id": object_id})
        else:
            await self._request("store_seal", {"object_id": object_id})

    async def get(self, object_id: bytes, timeout: Optional[float] = None
                  ) -> Optional[Tuple[memoryview, bytes]]:
        """Returns (zero-copy memoryview, metadata) or None on timeout.

        The object stays pinned until `release(object_id)` is called.
        """
        desc = await self._request(
            "store_get", {"object_id": object_id, "timeout": timeout})
        if desc is None:
            return None
        name, offset, size, metadata = desc
        shm = self._segment(name)
        return memoryview(shm.buf)[offset : offset + size], metadata

    async def release(self, object_id: bytes):
        await self._request("store_release", {"object_id": object_id})

    async def contains(self, object_id: bytes) -> bool:
        return await self._request("store_contains", {"object_id": object_id})

    async def delete(self, object_ids: List[bytes]):
        await self._request("store_delete", {"object_ids": object_ids})

    def close(self):
        for shm in self._segments.values():
            try:
                shm.close()
            except BufferError:
                # Zero-copy arrays deserialized out of this segment are still
                # alive in user code; leak the mapping (the OS reclaims it at
                # process exit) instead of invalidating their memory.
                if isinstance(shm, shared_memory.SharedMemory):
                    shm._buf = None   # noqa: SLF001 — silence __del__
                    shm._mmap = None  # noqa: SLF001
            except Exception:
                pass
        self._segments.clear()
