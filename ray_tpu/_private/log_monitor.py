"""Log monitor: tails worker log files and publishes new lines to the GCS
"logs" pubsub channel; drivers subscribe and echo them with a
"(worker=... node=...)" prefix.

Reference: python/ray/_private/log_monitor.py:103 (LogMonitor tails
/tmp/ray/session_*/logs and publishes over GCS pubsub — the `(pid=...)`
stream every Ray user knows). One monitor runs inside each raylet.
"""

from __future__ import annotations

import asyncio
import glob
import logging
import os
from typing import Callable, Dict, Optional

logger = logging.getLogger(__name__)

MAX_LINES_PER_BATCH = 200
MAX_LINE_LEN = 4096


class LogMonitor:
    def __init__(self, session_dir: str, node_name: str,
                 publish,  # async callable(message: dict)
                 pid_of: Optional[Callable[[str], int]] = None,
                 owns: Optional[Callable[[str], bool]] = None,
                 interval_s: float = 0.25):
        self.log_dir = os.path.join(session_dir, "logs")
        self.node_name = node_name
        self.publish = publish
        self.pid_of = pid_of or (lambda wid: -1)
        # Multiple raylets can share one session dir (fake cluster): each
        # monitor tails only the workers its raylet spawned.
        self.owns = owns or (lambda wid: True)
        self.interval_s = interval_s
        self._offsets: Dict[str, int] = {}
        self._task: Optional[asyncio.Task] = None

    def start(self):
        self._task = asyncio.ensure_future(self._run())

    def stop(self):
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self):
        # Skip history that predates this monitor (e.g. a restarted raylet
        # sharing the session dir): start tailing from current EOF.
        for path in glob.glob(os.path.join(self.log_dir, "worker-*.log")):
            try:
                self._offsets[path] = os.path.getsize(path)
            except OSError:
                pass
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                batches = self._scan()
            except Exception:  # noqa: BLE001
                logger.exception("log monitor scan failed")
                continue
            for worker_hex, lines in batches:
                try:
                    await self.publish({
                        "node": self.node_name,
                        "worker": worker_hex,
                        "pid": self.pid_of(worker_hex),
                        "lines": lines,
                    })
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001
                    # Transient GCS failure: this batch is lost (best-effort
                    # stream) but the monitor keeps running — the raylet's
                    # reconnect loop restores the connection underneath us.
                    break

    def _scan(self):
        batches = []
        for path in glob.glob(os.path.join(self.log_dir, "worker-*.log")):
            if not self.owns(os.path.basename(path)
                             [len("worker-"):-len(".log")]):
                continue
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(path, 0)
            if size <= offset:
                if size < offset:           # truncated/rotated
                    self._offsets[path] = 0
                continue
            try:
                # ray-tpu: noqa(ASYNC-BLOCK): dedicated monitor loop; tailing log files IS its only duty
                with open(path, "rb") as f:
                    f.seek(offset)
                    data = f.read(1 << 20)
            except OSError:
                continue
            # Only consume complete lines; partial tail stays for next scan
            # — unless the read window is full (a single line >1 MiB with
            # no newline would otherwise stall this file forever): consume
            # the whole window as one truncated line.
            end = data.rfind(b"\n")
            if end < 0:
                if len(data) < (1 << 20):
                    continue
                end = len(data) - 1
            self._offsets[path] = offset + end + 1
            lines = [ln.decode("utf-8", "replace")[:MAX_LINE_LEN]
                     for ln in data[:end].split(b"\n")]
            worker_hex = os.path.basename(path)[len("worker-"):-len(".log")]
            # Chunk (don't drop) bursts: every line ships, bounded per
            # message; the 1 MiB read above bounds a single scan.
            for i in range(0, len(lines), MAX_LINES_PER_BATCH):
                batches.append((worker_hex,
                                lines[i:i + MAX_LINES_PER_BATCH]))
        return batches
