"""Memory monitor + worker-killing policy (OOM defense).

Reference: src/ray/common/memory_monitor.h (threshold polling of system
memory) and src/ray/raylet/worker_killing_policy_group_by_owner.h (victim
selection: group leased workers by submitting owner, kill the newest
worker of the largest group, so one runaway map_batches does not take the
whole node down). The raylet runs one monitor; the usage reader is
injectable so tests can simulate pressure deterministically.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)


def system_memory_usage_fraction() -> float:
    """1 - MemAvailable/MemTotal from /proc/meminfo (Linux)."""
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = float(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = float(line.split()[1])
                if total is not None and avail is not None:
                    break
    except OSError:
        return 0.0
    if not total:
        return 0.0
    return 1.0 - (avail or 0.0) / total


def process_rss_bytes(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * 4096
    except (OSError, ValueError, IndexError):
        return 0


def pick_victim(workers: List) -> Optional[object]:
    """Group-by-owner policy over leased worker handles.

    Expects objects with .leased, .is_actor_worker, .lease_owner,
    .idle_since (last grant time), .pid. Returns the newest worker of the
    owner with the most leased workers; task workers are preferred over
    actor workers (actors lose state on kill). Workers pinned by a
    compiled DAG (.dag_pins non-empty) are never victims: killing one
    wedges every tick of its pipeline, a far worse outcome than letting
    a retryable task die.
    """
    leased = [w for w in workers
              if w.leased and not getattr(w, "dag_pins", None)]
    if not leased:
        return None
    for pool in ([w for w in leased if not w.is_actor_worker],
                 [w for w in leased if w.is_actor_worker]):
        if not pool:
            continue
        groups: dict = {}
        for w in pool:
            groups.setdefault(getattr(w, "lease_owner", ""), []).append(w)
        biggest = max(groups.values(), key=len)
        return max(biggest, key=lambda w: w.idle_since)
    return None


class MemoryMonitor:
    def __init__(self, threshold: float, interval_s: float,
                 on_pressure: Callable[[float], None],
                 usage_reader: Optional[Callable[[], float]] = None):
        self.threshold = threshold
        self.interval_s = interval_s
        self.on_pressure = on_pressure
        self.usage_reader = usage_reader or system_memory_usage_fraction
        self._task: Optional[asyncio.Task] = None

    def start(self):
        self._task = asyncio.ensure_future(self._run())

    def stop(self):
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self):
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                usage = self.usage_reader()
                if usage >= self.threshold:
                    self.on_pressure(usage)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                logger.exception("memory monitor tick failed")
