"""GCS: the cluster metadata authority.

Capability parity with the reference GCS server (src/ray/gcs/gcs_server/):
node table + health checking (GcsNodeManager / GcsHealthCheckManager), actor
management with restart-driven FSM (GcsActorManager), placement groups
(GcsPlacementGroupManager + bundle scheduling policies), job table
(GcsJobManager), KV store (GcsKvManager, backs the function table and
runtime-env URIs), pubsub broadcast (src/ray/pubsub/), cluster resource view
sync (GcsResourceManager + ray_syncer.h), named actors, and task-event
collection (GcsTaskManager) for the state API.

Single asyncio process; all state in memory with optional snapshot persistence
(GCS fault tolerance: snapshot + restart, the Redis-equivalent is a file).
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import time
from typing import Dict, List, Optional

from ray_tpu._private import rpc
from ray_tpu._private.common import (ACTOR_ALIVE, ACTOR_DEAD, ACTOR_PENDING,
                                     ACTOR_RESTARTING, PG_CREATED, PG_PENDING,
                                     PG_REMOVED, PG_RESCHEDULING, ActorInfo,
                                     JobInfo, NodeInfo, PlacementGroupInfo)
from ray_tpu._private.config import Config
from ray_tpu._private.ids import ActorID, JobID, NodeID, PlacementGroupID

logger = logging.getLogger(__name__)


class Pubsub:
    """Channel-based pubsub over persistent RPC connections.

    Equivalent to src/ray/pubsub/publisher.h: subscribers register channels on
    their connection; publishes push to every subscribed live connection.
    """

    def __init__(self, max_outbox: int = 2000):
        # channel -> set of connections
        self._subs: Dict[str, set] = {}
        # Slow-consumer protection (ROADMAP follow-on): once a subscriber's
        # transport buffer backs up, its frames divert into a bounded
        # per-connection outbox drained by a flusher that respects the
        # socket's backpressure. Past the cap the OLDEST frame drops —
        # a stalled subscriber costs O(max_outbox), not unbounded memory.
        self.max_outbox = max(1, int(max_outbox))
        self._outboxes: Dict[rpc.Connection, object] = {}  # conn -> deque
        self._flushing: set = set()
        self.dropped_total = 0

    def subscribe(self, conn: rpc.Connection, channels: List[str]):
        for ch in channels:
            self._subs.setdefault(ch, set()).add(conn)
        prev = conn.on_close
        def _cleanup(c, _prev=prev):
            self.drop_connection(c)
            if _prev:
                _prev(c)
        conn.on_close = _cleanup

    def unsubscribe(self, conn: rpc.Connection, channels: List[str]):
        for ch in channels:
            self._subs.get(ch, set()).discard(conn)

    def drop_connection(self, conn: rpc.Connection):
        for subs in self._subs.values():
            subs.discard(conn)
        self._outboxes.pop(conn, None)
        self._flushing.discard(conn)

    def outbox_depths(self) -> Dict[str, int]:
        """Per-subscriber backlog depth (observability surface)."""
        return {f"conn-{id(conn) & 0xffffff:06x}": len(box)
                for conn, box in self._outboxes.items()}

    def publish(self, channel: str, message):
        """Fan a message out to every live subscriber, synchronously.

        Fast path: push_nowait queues one frame per subscriber;
        everything published within the same loop tick coalesces into a
        single BATCH envelope per subscriber connection (one pickle + one
        write), so a publish storm costs the GCS O(ticks), not
        O(messages) — and no coroutine is spawned per (message,
        subscriber) pair. Subscribers whose socket has backed up divert
        to the bounded outbox instead (see __init__)."""
        conns = self._subs.get(channel)
        if not conns:
            return
        payload = {"channel": channel, "message": message}
        for conn in list(conns):
            if conn.closed:
                conns.discard(conn)
                continue
            try:
                self._deliver(conn, payload)
            except Exception:  # noqa: BLE001 — subscriber died mid-publish
                self.drop_connection(conn)

    def _deliver(self, conn: rpc.Connection, payload: dict):
        box = self._outboxes.get(conn)
        if box is None:
            if not conn.write_backed_up():
                conn.push_nowait("pub", payload)   # healthy: zero-copy path
                return
            from collections import deque
            box = self._outboxes[conn] = deque()
        box.append(payload)
        if len(box) > self.max_outbox:
            box.popleft()
            self.dropped_total += 1
        if conn not in self._flushing:
            self._flushing.add(conn)
            asyncio.ensure_future(self._flush_outbox(conn))

    async def _flush_outbox(self, conn: rpc.Connection):
        """Drain one subscriber's backlog at the pace its socket accepts
        (conn.push awaits drain past the transport high-water mark).
        Frames published while a backlog exists append to it, preserving
        per-subscriber delivery order."""
        try:
            while not conn.closed:
                box = self._outboxes.get(conn)
                if not box:
                    break
                await conn.push("pub", box.popleft())
        except Exception:  # noqa: BLE001 — subscriber died mid-drain
            self.drop_connection(conn)
        finally:
            self._flushing.discard(conn)
            box = self._outboxes.get(conn)
            if not box:
                self._outboxes.pop(conn, None)


class GcsServer:
    def __init__(self, config: Config, session_dir: str = ""):
        self.config = config
        self.session_dir = session_dir
        self.server = rpc.RpcServer("gcs")
        self.pubsub = Pubsub(max_outbox=config.pubsub_max_outbox)
        self.clients = rpc.ClientPool()

        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[tuple, ActorID] = {}   # (namespace, name) -> id
        self.jobs: Dict[JobID, JobInfo] = {}
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.kv: Dict[str, Dict[bytes, bytes]] = {}     # namespace -> {key: val}
        self.node_demand: Dict[NodeID, list] = {}       # queued lease shapes
        self.metrics_reports: Dict[str, list] = {}      # reporter -> snapshot
        # Telemetry plane: per-reporter delta-frame decoders feeding the
        # cluster time-series store. The epoch tags this GCS incarnation;
        # agents that shipped frames to a previous incarnation see the
        # mismatch in the reply and re-send interned definitions.
        from ray_tpu._private import tsdb as _tsdb
        self.tsdb = _tsdb.TSDB(retention_s=config.tsdb_retention_s,
                               resolution_s=config.tsdb_resolution_s,
                               max_series=config.tsdb_max_series)
        self.metrics_frames: Dict[str, list] = {}   # reporter -> (ts, decoder)
        self._tsdb_epoch = os.urandom(6).hex()
        self._tsdb_task: Optional[asyncio.Task] = None
        self.metrics_http_address = ""
        self._http_server = None
        self.task_events: List[dict] = []
        self._job_counter = 0
        self._autoscaler_seen = 0.0   # last get_autoscaler_state poll
        self._pg_lock = asyncio.Lock()
        self._actor_reschedule_lock = asyncio.Lock()
        # Drain protocol state: futures resolved when a node goes dead, and
        # the per-node deadline watchers.
        self._drain_waiters: Dict[NodeID, List[asyncio.Future]] = {}
        self._drain_tasks: Dict[NodeID, asyncio.Task] = {}
        # Slice fault domains: one drain/migration task per draining gang
        # (keyed by slice_id), plus lifetime counters for the gang paths.
        self._gang_tasks: Dict[str, asyncio.Task] = {}
        # Post-deadline "replacement READY" watchers: gang recovery is
        # counted when the replacement domain actually serves (PGs
        # re-committed AND migrated actors' constructors done), which can
        # land well after the drain deadline.
        self._recovery_tasks: set = set()
        self.gang_drains_total = 0
        self.gang_recoveries_total = 0
        # Compiled-DAG index: dag_id -> set of participant NodeIDs,
        # maintained by the owning core worker at pin/release time. A
        # (gang-)drain notice resolves the affected DAGs here and stamps
        # their ids into the published event, so every driver's drain
        # listener matches on one set-membership check instead of
        # cross-referencing node ids.
        self._dag_index: Dict[str, set] = {}
        # Consecutive failed reserve-before-release attempts per PG (the
        # release-and-replace liveness backstop in _schedule_pg).
        self._pg_handoff_failures: Dict[PlacementGroupID, int] = {}
        # Batched actor-creation pipeline (GcsActorScheduler): PENDING
        # creations queue here; one loop drains ALL due entries per pass,
        # places them against a debited planning view, hints the
        # destination raylets' warm pools, and fans creates out
        # concurrently bounded per raylet.
        self._creation_queue: List[tuple] = []   # (ready_time, ActorInfo)
        self._creation_wakeup = asyncio.Event()
        self._creation_task: Optional[asyncio.Task] = None
        self._create_sems: Dict[NodeID, asyncio.Semaphore] = {}
        # Outstanding create_actor RPCs per node: a cold storm (no warm
        # capacity anywhere) spreads by this instead of packing onto the
        # one most-utilized node whose zygote then forks the whole storm
        # serially.
        self._creates_inflight: Dict[NodeID, int] = {}
        # (actor_id, num_restarts) incarnations with a create in flight:
        # duplicate enqueues that land in different passes are dropped
        # here instead of driving two concurrent creates on two nodes.
        self._creating: set = set()
        # ALIVE pubsub coalescing: creations completing in the same loop
        # tick publish ONE "alive_batch" frame.
        self._alive_buf: List[ActorInfo] = []
        self._alive_flush_scheduled = False
        self.alive_frames_published = 0
        self._health_task: Optional[asyncio.Task] = None
        self._persist_task: Optional[asyncio.Task] = None
        self._lag_task: Optional[asyncio.Task] = None
        self._dirty = False
        self._ext_store = None  # ExternalStoreClient when configured
        self.address = ""

    async def start(self, host: str = "127.0.0.1", port: int = 0,
                    restore: bool = True) -> str:
        """Start the server; restore==True replays the session snapshot if
        one exists (head fault tolerance — reference:
        src/ray/gcs/store_client/redis_store_client.h persistence +
        gcs reconnect, ray_config_def.h:441)."""
        if self.config.gcs_storage_address:
            from ray_tpu._private.kv_store import ExternalStoreClient
            self._ext_store = ExternalStoreClient(
                self.config.gcs_storage_address, pool=self.clients)
        if restore:
            restored = False
            if self._ext_store is not None:
                restored = await self._maybe_restore_external()
            if not restored:
                self._maybe_restore()
        self.server.register_all(self)
        actual = await self.server.start(host, port)
        self.address = f"{host}:{actual}"
        # Re-arm deadline watchers for nodes restored mid-drain: without
        # this a DRAINING node would sit unschedulable forever after a GCS
        # restart (its drain task died with the old process). Draining
        # members of one slice re-arm as a single gang task so the
        # migration unit survives the restart too.
        regang: Dict[str, List[NodeID]] = {}
        for node_id, info in self.nodes.items():
            if info.alive and info.draining:
                if info.slice_id:
                    regang.setdefault(info.slice_id, []).append(node_id)
                else:
                    self._drain_tasks[node_id] = asyncio.ensure_future(
                        self._drain_node_task(node_id, 0.0))
        for slice_id, members in regang.items():
            self._gang_tasks[slice_id] = asyncio.ensure_future(
                self._drain_gang_task(slice_id, members, 0.0))
        # Re-drive actor creations restored mid-flight: a snapshot taken
        # before a creation completed leaves the row PENDING_CREATION with
        # no _schedule_actor task alive (it died with the old process),
        # and the worker's eventual death report can't help — the restored
        # record has no worker bound. Same re-arm treatment as the drain
        # tasks above; RESTARTING rows lost their reschedule task the same
        # way. _schedule_actor retries until a node is feasible, so firing
        # before raylets re-register is safe.
        self._creation_task = asyncio.ensure_future(
            self._actor_creation_loop())
        for actor in self.actors.values():
            if actor.state in (ACTOR_PENDING, ACTOR_RESTARTING):
                self._enqueue_creation(actor)
        self._health_task = asyncio.ensure_future(self._health_loop())
        if self.session_dir or self._ext_store is not None:
            self._persist_task = asyncio.ensure_future(self._persist_loop())
        # The GCS claims the process's single metrics-reporter slot: when
        # raylets and a driver core share this process (local init), their
        # snapshots of the SAME registry must not be pushed on top of the
        # local merge below (double counting).
        from ray_tpu.util import metrics as _metrics
        _metrics.claim_reporter(self, force=True)
        self._lag_task = _metrics.start_loop_lag_probe("gcs")
        # The head process's own registry never rides a frame (the claim
        # above suppresses every co-resident agent), so a local sampler
        # feeds it into the tsdb at the store's native resolution.
        self._tsdb_task = asyncio.ensure_future(self._tsdb_local_loop())
        await self._start_http(host)
        logger.info("GCS started at %s", self.address)
        return self.address

    async def stop(self):
        from ray_tpu.util import metrics as _metrics
        _metrics.release_reporter(self)
        for task in self._drain_tasks.values():
            task.cancel()
        for task in self._gang_tasks.values():
            task.cancel()
        for task in list(self._recovery_tasks):
            task.cancel()
        if self._health_task:
            self._health_task.cancel()
        if self._creation_task:
            self._creation_task.cancel()
        if self._persist_task:
            self._persist_task.cancel()
        if self._lag_task:
            self._lag_task.cancel()
        if self._tsdb_task:
            self._tsdb_task.cancel()
        if self._http_server is not None:
            self._http_server.close()
        await self.server.stop()
        await self.clients.close_all()

    # ------------- persistence plumbing -------------

    def _mark_dirty(self):
        self._dirty = True

    def _snapshot_path(self) -> str:
        return os.path.join(self.session_dir, "gcs_snapshot.bin")

    def _maybe_restore(self):
        path = self._snapshot_path() if self.session_dir else ""
        if not path or not os.path.exists(path):
            return
        with open(path, "rb") as f:
            self.restore(f.read())
        now = time.time()
        for info in self.nodes.values():
            # Give every restored node a fresh heartbeat window to reconnect
            # before the health loop declares it dead.
            info.last_heartbeat = now
            # Actor-liveness reconcile: a worker that died while this GCS
            # was down reported to nobody (the raylet's one-shot death
            # report swallows RpcError), so its actor is restored ALIVE
            # forever. Each node's next heartbeat is asked to send the
            # live worker set once; rpc_reconcile_actors restarts the
            # orphaned actors (registry + restore interplay).
            info.needs_actor_reconcile = True
        logger.info("GCS restored %d nodes / %d actors / %d PGs from %s",
                    len(self.nodes), len(self.actors),
                    len(self.placement_groups), path)

    def _ext_key(self) -> str:
        return f"gcs_snapshot:{self.config.gcs_storage_namespace}"

    async def _maybe_restore_external(self) -> bool:
        """Recover state from the external store (Redis-equivalent). The
        external copy wins over any local file: it is the one a head
        restarted on a different machine can still reach."""
        try:
            blob = await self._ext_store.get(self._ext_key())
        except Exception:
            logger.exception("external store unreachable at startup; "
                             "falling back to local snapshot")
            return False
        if blob is None:
            return False
        self.restore(blob)
        now = time.time()
        for info in self.nodes.values():
            info.last_heartbeat = now
            info.needs_actor_reconcile = True  # see _maybe_restore
        logger.info("GCS restored %d nodes / %d actors / %d PGs from "
                    "external store %s", len(self.nodes), len(self.actors),
                    len(self.placement_groups),
                    self.config.gcs_storage_address)
        return True

    async def _persist_loop(self):
        while True:
            await asyncio.sleep(self.config.heartbeat_interval_s)
            if self._dirty:
                self._dirty = False
                blob = None
                try:
                    blob = self.snapshot()
                    if self.session_dir:
                        # Write on the executor: the blob is already
                        # built, and the atomic tmp+replace write must
                        # not stall the GCS loop on a slow disk (every
                        # control RPC in the cluster queues behind it).
                        # The loop task is single, so writes stay
                        # ordered.
                        await asyncio.get_running_loop().run_in_executor(
                            None, self.save_snapshot, "", blob)
                except Exception:
                    logger.exception("GCS snapshot failed")
                if self._ext_store is not None and blob is not None:
                    try:
                        await self._ext_store.set(self._ext_key(), blob)
                    except Exception:
                        # Re-arm the dirty flag: the external copy is now
                        # stale and restore prefers it, so it MUST be
                        # retried next tick even with no new mutations.
                        self._dirty = True
                        logger.exception("external store write failed")

    # ------------- node management -------------

    @rpc.idempotent
    async def rpc_register_node(self, conn, payload) -> dict:
        info: NodeInfo = payload["node_info"]
        prev = self.nodes.get(info.node_id)
        if prev is not None and prev.alive:
            # Replay of a registration that already executed (reply lost
            # with the connection): carry over the GCS-side mutable state
            # the replay payload cannot know about. Without this, a
            # drain begun in the redial window (e.g. the node's slice
            # gang-draining off a preemption notice) would be silently
            # undone and the node would keep taking work.
            info.draining = prev.draining
            info.drain_deadline = prev.drain_deadline
            info.resources_available = prev.resources_available
            # A replayed registration must not lose a pending
            # post-restore reconcile ask (cleared below when the payload
            # carries the live set).
            if getattr(prev, "needs_actor_reconcile", False):
                info.needs_actor_reconcile = True
        self.nodes[info.node_id] = info
        if "live_worker_ids" in payload:
            # (Re)registration doubles as the actor-liveness reconcile:
            # after a GCS restart the raylet's reconnect lands here, and
            # ALIVE actors whose workers died during the outage get
            # their (lost) failure reports re-driven now.
            self._reconcile_node_actors(
                info.node_id, set(payload.get("live_worker_ids") or []))
            info.needs_actor_reconcile = False
        logger.info("node %s registered at %s (resources=%s)",
                    info.node_id.hex()[:12], info.address, info.resources_total)
        self.pubsub.publish("nodes", {"event": "alive", "node_info": info})
        self._mark_dirty()
        self._publish_resources(info)
        return {"node_id": info.node_id, "config": self.config.to_dict(),
                "cluster_view": self._resource_view()}

    def _publish_resources(self, info: NodeInfo):
        self.pubsub.publish("resources", {
            "node_id": info.node_id,
            "available": info.resources_available,
            "total": info.resources_total,
            "address": info.address,
            "labels": info.labels,
            "draining": info.draining,
        })

    def _resource_view(self) -> dict:
        # Draining nodes are excluded: a freshly registered raylet must not
        # learn a peer that is on its way out as a spillback target.
        return {
            n.node_id: {"available": n.resources_available,
                        "total": n.resources_total, "address": n.address,
                        "labels": n.labels}
            for n in self.nodes.values() if n.alive and not n.draining
        }

    @staticmethod
    def _schedulable(n: NodeInfo) -> bool:
        return n.alive and not n.draining

    @rpc.idempotent
    async def rpc_heartbeat(self, conn, payload):
        node_id = payload["node_id"]
        info = self.nodes.get(node_id)
        if info is None:
            return {"reregister": True}
        info.last_heartbeat = time.time()
        if "resources_available" in payload:
            info.resources_available = payload["resources_available"]
        if "pending_demand" in payload:
            self.node_demand[node_id] = payload["pending_demand"]
        if "idle_workers" in payload:
            info.idle_workers = payload["idle_workers"]
        # Raylets queue (instead of fail) infeasible leases only while an
        # autoscaler is polling — it may be about to add the node.
        return {"reregister": False,
                # Post-restore handshake: ask this node for its live
                # worker set once so ALIVE actors whose workers died
                # during the GCS outage get restarted (their one-shot
                # death reports were lost with the old process).
                "report_actors":
                    getattr(info, "needs_actor_reconcile", False),
                "autoscaler_active":
                    time.time() - self._autoscaler_seen < 60.0}

    def _reconcile_node_actors(self, node_id, live: set) -> int:
        """Registry + restore interplay: any ALIVE actor bound to this
        node whose worker is not in the reported live set lost its death
        report to a GCS restart — put it through the normal failure
        path (restart per max_restarts) now instead of never."""
        fixed = 0
        for actor in list(self.actors.values()):
            if (actor.state == ACTOR_ALIVE and actor.node_id == node_id
                    and actor.worker_id is not None
                    and actor.worker_id not in live):
                logger.warning(
                    "actor %s lost its worker while the GCS was down; "
                    "driving the failure path now",
                    actor.actor_id.hex()[:12])
                asyncio.ensure_future(self._handle_actor_failure(
                    actor, "worker lost during GCS restart"))
                fixed += 1
        return fixed

    @rpc.idempotent
    async def rpc_reconcile_actors(self, conn, payload):
        """The raylet's answer to a `report_actors` heartbeat flag (the
        backstop path; registration carries the same live set inline)."""
        node_id = payload["node_id"]
        info = self.nodes.get(node_id)
        if info is not None:
            info.needs_actor_reconcile = False
        return self._reconcile_node_actors(
            node_id, set(payload.get("live_worker_ids") or []))

    # ------------- metrics / observability plane -------------

    async def _start_http(self, host: str):
        """Tiny HTTP endpoint: /metrics (Prometheus text) and /api/status
        (JSON) — reference: metrics_agent.py Prometheus exporter +
        dashboard REST, scoped to the head."""
        async def handle(reader, writer):
            try:
                request_line = await asyncio.wait_for(reader.readline(), 5)
                parts = request_line.decode("latin1").split()
                path = parts[1] if len(parts) >= 2 else "/"
                while (await asyncio.wait_for(reader.readline(), 5)) \
                        not in (b"\r\n", b"\n", b""):
                    pass
                from urllib.parse import parse_qs, urlsplit
                q = parse_qs(urlsplit(path).query)
                api_routes = {
                    "/api/status": self._status_summary,
                    "/api/actors": self._actors_table,
                    "/api/jobs": self._jobs_table,
                    "/api/pgs": self._pgs_table,
                    "/api/tasks": self._tasks_summary,
                    "/api/latency": self._latency_summary,
                    "/api/timeline": self._timeline_trace,
                    "/api/logs": self._logs_index,
                    "/api/logtail": lambda: self._log_tail(
                        q.get("file", [""])[0],
                        int(q.get("n", ["200"])[0] or 200)),
                    "/api/metrics/query": lambda: self.tsdb.query(
                        q.get("name", [""])[0],
                        tags={k[4:]: v[0] for k, v in q.items()
                              if k.startswith("tag.")},
                        window_s=float(q.get("window", ["300"])[0] or 300),
                        fold=q.get("fold", ["value"])[0]),
                    "/api/metrics/series": lambda: {
                        "names": self.tsdb.series_names(),
                        "resolution_s": self.tsdb.res},
                    "/api/traces": lambda: self._traces_search(
                        deployment=q.get("deployment", [""])[0],
                        min_ms=float(q.get("min_ms", ["0"])[0] or 0),
                        errors_only=q.get("errors_only", ["0"])[0]
                        in ("1", "true"),
                        limit=int(q.get("limit", ["100"])[0] or 100)),
                }
                route = next((fn for p, fn in api_routes.items()
                              if urlsplit(path).path == p), None)
                if path.startswith("/metrics"):
                    from ray_tpu.util import metrics as m
                    body = m.to_prometheus(self._merged_metrics())
                    ctype = "text/plain; version=0.0.4"
                    code = "200 OK"
                elif route is not None:
                    import json as _json
                    body = _json.dumps(route(), default=str)
                    ctype = "application/json"
                    code = "200 OK"
                elif path == "/" or path.startswith("/dashboard"):
                    body = _DASHBOARD_HTML
                    ctype = "text/html"
                    code = "200 OK"
                else:
                    body, ctype, code = "not found", "text/plain", "404 Not Found"
                data = body.encode()
                writer.write(
                    f"HTTP/1.1 {code}\r\nContent-Type: {ctype}\r\n"
                    f"Content-Length: {len(data)}\r\n"
                    f"Connection: close\r\n\r\n".encode() + data)
                await writer.drain()
            except Exception:  # noqa: BLE001
                pass
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        try:
            self._http_server = await asyncio.start_server(handle, host, 0)
            port = self._http_server.sockets[0].getsockname()[1]
            self.metrics_http_address = f"{host}:{port}"
        except Exception:  # noqa: BLE001
            logger.exception("metrics HTTP endpoint failed to start")

    def _internal_metrics(self) -> list:
        g = []

        def gauge(name, value, desc="", **tags):
            g.append({"name": name, "type": "gauge", "description": desc,
                      "tags": tags, "value": float(value)})

        gauge("ray_tpu_nodes_alive",
              sum(1 for n in self.nodes.values() if n.alive),
              "alive raylets")
        gauge("ray_tpu_nodes_draining",
              sum(1 for n in self.nodes.values()
                  if n.alive and n.draining), "draining raylets")
        for state in (ACTOR_ALIVE, ACTOR_PENDING, ACTOR_RESTARTING,
                      ACTOR_DEAD):
            gauge("ray_tpu_actors", sum(
                1 for a in self.actors.values() if a.state == state),
                "actors by state", State=state)
        gauge("ray_tpu_placement_groups", len([
            p for p in self.placement_groups.values()
            if p.state != PG_REMOVED]), "live placement groups")
        gauge("ray_tpu_jobs_alive",
              sum(1 for j in self.jobs.values() if j.alive), "alive jobs")
        totals: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.resources_total.items():
                totals[k] = totals.get(k, 0.0) + v
            for k, v in n.resources_available.items():
                avail[k] = avail.get(k, 0.0) + v
        for k in totals:
            gauge("ray_tpu_resource_total", totals[k], "", Resource=k)
            gauge("ray_tpu_resource_available", avail.get(k, 0.0), "",
                  Resource=k)
        # Pubsub fan-out health (slow-consumer outboxes; tentpole gauges).
        g.append({"name": "ray_tpu_pubsub_dropped_total", "type": "counter",
                  "description": "pubsub frames dropped for stalled "
                                 "subscribers (oldest-first past the "
                                 "outbox cap)",
                  "tags": {}, "value": float(self.pubsub.dropped_total)})
        for sub, depth in self.pubsub.outbox_depths().items():
            gauge("ray_tpu_pubsub_outbox_depth", depth,
                  "queued pubsub frames per slow subscriber",
                  Subscriber=sub)
        gauge("ray_tpu_task_events_buffered", len(self.task_events),
              "task events held in the GCS ring buffer")
        gauge("ray_tpu_tsdb_series", self.tsdb.n_series,
              "series held in the cluster time-series store")
        g.append({"name": "ray_tpu_tsdb_dropped_series_total",
                  "type": "counter",
                  "description": "series refused by the tsdb cardinality "
                                 "bound (tsdb_max_series)",
                  "tags": {}, "value": float(self.tsdb.dropped_total)})
        # Per-node CPU pressure for `ray_tpu top` (the cluster-wide
        # Resource gauges above have no Node axis).
        for n in self.nodes.values():
            if not n.alive:
                continue
            tot = n.resources_total.get("CPU", 0.0)
            if tot > 0:
                used = tot - n.resources_available.get("CPU", 0.0)
                gauge("ray_tpu_node_cpu_used_frac", used / tot,
                      "fraction of a node's CPU slots leased out",
                      Node=n.node_id.hex()[:12])
        # Slice fault domains: gang drains started / gangs whose
        # replacement domain became ready within the drain window.
        g.append({"name": "ray_tpu_gang_drains_total", "type": "counter",
                  "description": "slice gang drains started",
                  "tags": {}, "value": float(self.gang_drains_total)})
        g.append({"name": "ray_tpu_gang_recoveries_total",
                  "type": "counter",
                  "description": "gang drains whose PGs re-placed on a "
                                 "replacement domain before the deadline",
                  "tags": {}, "value": float(self.gang_recoveries_total)})
        return g

    def _merged_metrics(self) -> list:
        from ray_tpu._private import rpc as _rpc
        from ray_tpu.util import metrics as m
        # Dead reporters (reaped workers, finished drivers) stop pushing;
        # drop their snapshots after a grace period so gauges don't sum
        # stale values forever and the table stays bounded.
        now = time.time()
        ttl = max(30.0, 10 * self.config.metrics_report_interval_s)
        for reporter in [r for r, (ts, _) in self.metrics_reports.items()
                         if now - ts > ttl]:
            del self.metrics_reports[reporter]
        for reporter in [r for r, (ts, _) in self.metrics_frames.items()
                         if now - ts > ttl]:
            del self.metrics_frames[reporter]
            self.tsdb.drop_reporter(reporter)
        snaps = [snap for _, snap in self.metrics_reports.values()]
        snaps.extend(dec.snapshot() for _, dec in self.metrics_frames.values())
        if m.claim_reporter(self):
            # This process's registry (GCS + any co-resident raylet/driver
            # core) is served locally; nobody else pushes it (see
            # claim_reporter), so add it exactly once here.
            _rpc.export_transport_metrics()
            snaps.append(m.snapshot())
        merged = m.merge_snapshots(snaps)
        return merged + self._internal_metrics()

    def _status_summary(self) -> dict:
        return {
            "gcs_address": self.address,
            "metrics_address": self.metrics_http_address,
            "nodes": [{
                "node_id": n.node_id.hex(), "alive": n.alive,
                "is_head": n.is_head, "address": n.address,
                "draining": n.draining, "slice_id": n.slice_id,
                "resources_total": n.resources_total,
                "resources_available": n.resources_available,
            } for n in self.nodes.values()],
            "actors_alive": sum(1 for a in self.actors.values()
                                if a.state == ACTOR_ALIVE),
            "jobs_alive": sum(1 for j in self.jobs.values() if j.alive),
            "pending_demand": sum(len(v) for v in self.node_demand.values()),
        }

    # ------------- dashboard REST tables (reference: dashboard/ REST
    # endpoints backed by the GCS tables; here rendered by the tabbed
    # /dashboard page) -------------

    def _actors_table(self) -> list:
        return [{
            "actor_id": a.actor_id.hex(), "name": a.name,
            "class_name": a.class_name, "state": a.state,
            "node_id": a.node_id.hex() if a.node_id else "",
            "address": a.address, "num_restarts": a.num_restarts,
            "namespace": a.namespace,
        } for a in self.actors.values()]

    def _jobs_table(self) -> list:
        return [{
            "job_id": j.job_id.hex(), "entrypoint": j.entrypoint,
            "alive": j.alive, "start_time": j.start_time,
            "end_time": j.end_time,
            "metadata": j.metadata,
        } for j in self.jobs.values()]

    def _pgs_table(self) -> list:
        return [{
            "pg_id": p.pg_id.hex(), "name": p.name,
            "strategy": p.strategy, "state": p.state,
            "bundles": len(p.bundles),
            "placed": len(p.bundle_nodes),
        } for p in self.placement_groups.values()]

    def _timeline_trace(self) -> list:
        """Chrome-trace events from the task-event buffer (server-side
        twin of ray_tpu.timeline(); feeds the dashboard timeline panel):
        per-task slices, phase sub-slices, and cross-process flow events
        assembled by the shared flightrec builder."""
        from ray_tpu._private import flightrec
        return flightrec.build_trace(self.task_events)

    def _latency_summary(self) -> list:
        """Per-(task name, phase) p50/p95 latency rows — the dashboard
        Latency panel and `ray_tpu summary`'s latency columns.

        Memoized for 2s: the fold walks the whole event ring (up to 100k
        rows) on the GCS loop, and the dashboard polls every 2s — without
        the cache a busy ring would stall heartbeat/pubsub handling on
        every poll (the very loop lag the recorder measures)."""
        from ray_tpu._private import flightrec
        now = time.time()
        cached = getattr(self, "_latency_cache", None)
        if cached is not None and now - cached[0] < 2.0:
            return cached[1]
        rows = flightrec.latency_summary(self.task_events)
        self._latency_cache = (now, rows)
        return rows

    @rpc.idempotent
    async def rpc_get_task_latency(self, conn, payload):
        return self._latency_summary()

    def _logs_dir(self) -> str:
        return os.path.join(self.session_dir, "logs") \
            if self.session_dir else ""

    def _logs_index(self) -> list:
        """Head-node log files (worker/raylet/driver streams). Per-node
        agents would extend this to remote nodes; the head covers the
        single-node and driver cases the dashboard panel needs."""
        d = self._logs_dir()
        if not d or not os.path.isdir(d):
            return []
        out = []
        for name in sorted(os.listdir(d)):
            p = os.path.join(d, name)
            try:
                out.append({"file": name, "bytes": os.path.getsize(p),
                            "mtime": os.path.getmtime(p)})
            except OSError:
                continue
        return out

    def _log_tail(self, fname: str, n_lines: int = 200) -> dict:
        d = self._logs_dir()
        # basename() strips any traversal; the join must stay inside the
        # session's logs dir (untrusted query input).
        safe = os.path.basename(fname or "")
        path = os.path.join(d, safe) if d else ""
        if not safe or not d or not os.path.isfile(path):
            return {"file": safe, "lines": [], "error": "not found"}
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 256 * 1024))
                tail = f.read().decode("utf-8", "replace")
        except OSError as e:
            return {"file": safe, "lines": [], "error": str(e)}
        lines = tail.splitlines()[-max(1, min(n_lines, 2000)):]
        return {"file": safe, "lines": lines}

    def _tasks_summary(self) -> list:
        """Counts by (task name, latest state) — `ray summary tasks`."""
        latest: Dict[tuple, str] = {}
        for e in self.task_events:
            if e.get("kind"):  # spans / serve_request rows aren't tasks
                continue
            key = (e.get("name", ""), e.get("task_id"))
            latest[key] = e.get("state", "")
        counts: Dict[tuple, int] = {}
        for (name, _tid), state in latest.items():
            counts[(name, state)] = counts.get((name, state), 0) + 1
        return [{"name": n, "state": s, "count": c}
                for (n, s), c in sorted(counts.items())]

    @rpc.idempotent
    async def rpc_report_metrics(self, conn, payload):
        # Legacy full-snapshot push (pre-delta-frame agents). Still feeds
        # the tsdb: ingest takes absolutes, so replays are harmless.
        self.metrics_reports[payload["reporter"]] = (time.time(),
                                                     payload["metrics"])
        self.tsdb.ingest(payload["reporter"], payload["metrics"])
        return True

    @rpc.idempotent
    async def rpc_report_metrics_frame(self, conn, payload):
        """MetricsAgent delta-frame ingest.

        Rows carry absolute cumulative values (idempotent on replay);
        delta/clamp accounting happens in the tsdb. The reply always
        carries this GCS incarnation's epoch — an agent that shipped to a
        previous incarnation resets its encoder and re-sends definitions;
        ``resync`` covers the same race within one incarnation (decoder
        evicted by the reporter TTL while the agent kept interning)."""
        from ray_tpu._private import tsdb as _tsdb
        reporter = payload["reporter"]
        entry = self.metrics_frames.get(reporter)
        dec = entry[1] if entry else _tsdb.FrameDecoder()
        try:
            changed = dec.decode(payload["frame"])
        except _tsdb.ResyncNeeded:
            return {"epoch": self._tsdb_epoch, "resync": True}
        self.metrics_frames[reporter] = (time.time(), dec)
        self.tsdb.ingest(reporter, changed)
        return {"epoch": self._tsdb_epoch, "resync": False}

    @rpc.idempotent
    async def rpc_metrics_query(self, conn, payload):
        """Aligned-window tsdb query; accepts one query or a batch.

        One query: ``{"name", "tags"?, "window_s"?, "fold"?}`` →
        ``[{"name","tags","type","points"}]``. Batch: ``{"queries":
        [...]}`` → list of those, one per query (how `ray_tpu top`
        fetches a whole refresh in one round trip)."""
        queries = payload.get("queries")
        single = queries is None
        if single:
            queries = [payload]
        out = [self.tsdb.query(q["name"], tags=q.get("tags"),
                               window_s=float(q.get("window_s", 300.0)),
                               fold=q.get("fold", "value"))
               for q in queries]
        return out[0] if single else out

    @rpc.idempotent
    async def rpc_metrics_series(self, conn, payload):
        return {"names": self.tsdb.series_names(),
                "n_series": self.tsdb.n_series,
                "dropped": self.tsdb.dropped_total,
                "resolution_s": self.tsdb.res}

    async def _tsdb_local_loop(self):
        from ray_tpu._private import rpc as _rpc
        from ray_tpu.util import metrics as m
        while True:
            await asyncio.sleep(self.tsdb.res)
            try:
                if m.claim_reporter(self):
                    _rpc.export_transport_metrics()
                    self.tsdb.ingest("gcs:local",
                                     m.snapshot() + self._internal_metrics())
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — sampler must outlive hiccups
                logger.exception("tsdb local sampler tick failed")

    # ------------- per-request trace search (over the task-event ring) ----

    def _traces_search(self, deployment: str = "", min_ms: float = 0.0,
                       errors_only: bool = False, limit: int = 100) -> list:
        """Group `serve_request` events by request id into searchable
        summaries (start, total ms, hops, replays, error) — the rows feed
        `ray_tpu timeline --request <id>` for the full phase view."""
        reqs: Dict[str, dict] = {}
        for e in self.task_events:
            if e.get("kind") != "serve_request":
                continue
            rid = e.get("request_id", "")
            r = reqs.get(rid)
            if r is None:
                r = reqs[rid] = {"request_id": rid,
                                 "trace_id": e.get("trace_id", ""),
                                 "deployment": "", "hops": [],
                                 "start": e["time"], "end": e["time"],
                                 "replays": 0, "error": ""}
            dep = e.get("deployment", "")
            if dep and not r["deployment"]:
                r["deployment"] = dep
            r["hops"].append(e.get("hop", ""))
            ts = [e["time"]] + [p for p in (e.get("phases") or []) if p]
            r["start"] = min(r["start"], min(ts))
            r["end"] = max(r["end"], max(ts))
            r["replays"] = max(r["replays"], e.get("replays", 0))
            if e.get("error"):
                r["error"] = e["error"]
        rows = []
        for r in reqs.values():
            r["total_ms"] = (r["end"] - r["start"]) * 1000.0
            if deployment and r["deployment"] != deployment:
                continue
            if r["total_ms"] < min_ms:
                continue
            if errors_only and not r["error"]:
                continue
            rows.append(r)
        rows.sort(key=lambda r: r["start"], reverse=True)
        return rows[:max(1, min(int(limit), 5000))]

    @rpc.idempotent
    async def rpc_search_traces(self, conn, payload):
        return self._traces_search(
            deployment=payload.get("deployment", ""),
            min_ms=float(payload.get("min_ms", 0.0)),
            errors_only=bool(payload.get("errors_only", False)),
            limit=int(payload.get("limit", 100)))

    @rpc.idempotent
    async def rpc_get_metrics_address(self, conn, payload):
        return self.metrics_http_address

    @rpc.idempotent
    async def rpc_get_status_summary(self, conn, payload):
        return self._status_summary()

    @rpc.idempotent
    async def rpc_get_autoscaler_state(self, conn, payload):
        """Cluster view for the autoscaler: per-node capacity/usage, queued
        lease demand, and unplaced placement groups (reference:
        gcs_autoscaler_state_manager.h GetClusterResourceState)."""
        self._autoscaler_seen = time.time()
        pending_pgs = [
            {"pg_id": pg.pg_id, "strategy": pg.strategy,
             "bundles": list(pg.bundles)}
            for pg in self.placement_groups.values()
            if pg.state in (PG_PENDING, PG_RESCHEDULING)]
        demand = []
        for node_id, shapes in self.node_demand.items():
            info = self.nodes.get(node_id)
            if info is not None and info.alive:
                demand.extend(shapes)
        return {
            "nodes": {
                n.node_id: {"total": n.resources_total,
                            "available": n.resources_available,
                            "alive": n.alive, "is_head": n.is_head,
                            "draining": n.draining,
                            "labels": n.labels}
                for n in self.nodes.values()},
            "pending_demand": demand,
            "pending_placement_groups": pending_pgs,
        }

    @rpc.idempotent
    async def rpc_get_all_nodes(self, conn, payload):
        return list(self.nodes.values())

    # ------------- drain protocol (planned node removal) -------------

    @rpc.idempotent
    async def rpc_drain_node(self, conn, payload):
        """Two-phase graceful removal (autoscaler downscale / preemption
        notice). Reference: gcs_node_manager DrainNode + DrainNodeReply.

        Phase 1 (immediately): the node stops receiving new leases, actor
        placements, and PG bundles; the raylet is told to finish running
        work and push its primary object copies to live peers; after a
        short grace window (save-on-preempt hook for Train) its actors are
        *migrated* — restarted elsewhere without charging max_restarts.
        Phase 2 (at the deadline, or as soon as the raylet reports idle):
        the node is marked dead.

        payload: node_id | node_id_hex, deadline_s (default 30), grace_s
        (default 0.5, actor-migration delay), wait (block until dead).
        Idempotent: re-draining a draining node only re-arms `wait`.

        Slice escalation: on TPU pods the failure unit is the slice, not
        the host — draining any member of a slice fault domain
        (NodeInfo.slice_id) atomically gang-drains EVERY member: one
        DRAINING transition with a shared deadline, gang-coherent lease
        rejection in the raylets, and PG/actor migration driven as a
        single unit (_drain_gang_task). A half-drained slice can never
        accept new work.
        """
        node_id = payload.get("node_id")
        if node_id is None and payload.get("node_id_hex"):
            node_id = next((n for n in self.nodes
                            if n.hex() == payload["node_id_hex"]), None)
        info = self.nodes.get(node_id) if node_id is not None else None
        if info is None:
            return False
        if not info.alive:
            return True
        deadline_s = float(payload.get("deadline_s", 30.0))
        grace_s = float(payload.get("grace_s", 0.5))
        if info.slice_id:
            self._start_gang_drain(info.slice_id, deadline_s, grace_s,
                                   payload.get("reason",
                                               "gang drain requested"))
            if payload.get("wait"):
                await self._wait_node_dead(
                    node_id, float(payload.get("wait_timeout_s",
                                               deadline_s + 10.0)))
            return True
        if not info.draining:
            info.draining = True
            info.drain_deadline = time.time() + deadline_s
            self._mark_dirty()
            logger.info("draining node %s (deadline in %.1fs)",
                        node_id.hex()[:12], deadline_s)
            self.pubsub.publish("nodes", {
                "event": "draining", "node_id": node_id,
                "address": info.address, "deadline": info.drain_deadline,
                "dag_ids": self._dags_on_nodes([node_id]),
                "reason": payload.get("reason", "drain requested")})
            # Tell the raylet: reject new lease grants, let running tasks
            # finish, push primary object copies to live nodes, report
            # drain_complete when idle.
            async def _notify_raylet():
                try:
                    await self.clients.request(
                        info.address, "drain",
                        {"deadline_s": deadline_s}, timeout=10.0)
                except Exception:  # noqa: BLE001 — raylet may already be gone
                    pass
            asyncio.ensure_future(_notify_raylet())
            self._drain_tasks[node_id] = asyncio.ensure_future(
                self._drain_node_task(node_id, grace_s))
        if payload.get("wait"):
            # wait_timeout_s lets callers with their own RPC deadline (the
            # autoscaler's sync bridge) bound the block below it.
            await self._wait_node_dead(
                node_id, float(payload.get("wait_timeout_s",
                                           deadline_s + 10.0)))
        return True

    async def _drain_node_task(self, node_id: NodeID, grace_s: float):
        """Migration + deadline watcher for one draining node."""
        info = self.nodes.get(node_id)
        if info is None:
            return
        # Grace window: workers on the node see the `draining` pubsub and
        # can act on it (Train's save-on-preempt checkpoint) before their
        # actors are torn down.
        if grace_s > 0:
            await asyncio.sleep(min(grace_s,
                                    max(0.0,
                                        info.drain_deadline - time.time())))
        if not info.alive:
            return
        # PG bundles on the node move first so PG-pinned actors have a live
        # bundle to migrate onto.
        for pg in list(self.placement_groups.values()):
            if pg.state == PG_CREATED and node_id in pg.bundle_nodes.values():
                await self._reschedule_pg(pg)
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in (ACTOR_ALIVE,
                                                            ACTOR_PENDING):
                await self._migrate_actor(
                    actor, f"node {node_id.hex()[:12]} draining")
        # Wait out the rest of the deadline; the raylet's drain_complete
        # normally beats this.
        remaining = info.drain_deadline - time.time()
        if remaining > 0:
            await asyncio.sleep(remaining)
        if info.alive:
            await self._mark_node_dead(node_id, reason="drain deadline",
                                       preempted=True)

    # ------------- slice fault domains (gang drain) -------------

    # ------------- compiled-DAG index (drain -> affected-DAG lookup) ----

    @rpc.idempotent
    async def rpc_dag_register(self, conn, payload):
        """Owning core worker reports a compiled DAG's participant nodes
        (at pin / re-pin time). Keyed upsert — replays and recovery
        re-registrations overwrite with the current footprint."""
        self._dag_index[payload["dag_id"]] = set(payload.get("node_ids")
                                                 or [])
        return True

    @rpc.idempotent
    async def rpc_dag_unregister(self, conn, payload):
        self._dag_index.pop(payload["dag_id"], None)
        return True

    def _dags_on_nodes(self, node_ids) -> List[str]:
        """dag_ids with at least one participant on `node_ids` — stamped
        into drain notices so drivers match on one membership check."""
        ids = set(node_ids)
        return sorted(d for d, nodes in self._dag_index.items()
                      if nodes & ids)

    def _slice_members(self, slice_id: str) -> List[NodeInfo]:
        return [n for n in self.nodes.values()
                if n.alive and n.slice_id == slice_id]

    def _start_gang_drain(self, slice_id: str, deadline_s: float,
                          grace_s: float, reason: str):
        """Atomically transition every alive member of a slice fault
        domain to DRAINING under one shared deadline.

        Synchronous up to (and including) the pubsub publishes — no await
        can interleave a lease grant or placement between two members'
        transitions, so the slice is never half-drained. Raylet notices
        (which carry the gang peer list for gang-coherent spill
        rejection) and the migration task follow asynchronously.
        """
        members = self._slice_members(slice_id)
        fresh = [n for n in members if not n.draining]
        if not fresh:
            return  # whole gang already draining: idempotent re-drain
        deadline = time.time() + deadline_s
        for n in fresh:
            n.draining = True
            n.drain_deadline = deadline
        self._mark_dirty()
        if len(fresh) == len(members):
            # First drain notice for this gang (not a member that joined
            # mid-drain): count the gang once.
            self.gang_drains_total += 1
        addresses = [n.address for n in members]
        member_ids = [n.node_id for n in members]
        logger.info("gang-draining slice %s: %d hosts (deadline in %.1fs)",
                    slice_id, len(members), deadline_s)
        self._record_gang_span(slice_id, "gang_drain_notice",
                               time.time(), time.time())
        # One gang event (gang-aware consumers: core worker retry
        # classification, Train) plus the per-member events every
        # single-node consumer already understands.
        affected_dags = self._dags_on_nodes(member_ids)
        self.pubsub.publish("nodes", {
            "event": "gang_draining", "slice_id": slice_id,
            "node_ids": member_ids, "addresses": addresses,
            "deadline": deadline, "reason": reason,
            "dag_ids": affected_dags})
        for n in fresh:
            self.pubsub.publish("nodes", {
                "event": "draining", "node_id": n.node_id,
                "address": n.address, "deadline": deadline,
                "reason": reason, "slice_id": slice_id,
                "dag_ids": affected_dags})

        async def _notify_raylet(node: NodeInfo):
            try:
                await self.clients.request(
                    node.address, "drain",
                    {"deadline_s": deadline_s,
                     "gang_addresses": [a for a in addresses
                                        if a != node.address]},
                    timeout=10.0)
            except Exception:  # noqa: BLE001 — raylet may already be gone
                pass

        for n in fresh:
            asyncio.ensure_future(_notify_raylet(n))
        prior = self._gang_tasks.get(slice_id)
        if prior is None or prior.done():
            self._gang_tasks[slice_id] = asyncio.ensure_future(
                self._drain_gang_task(slice_id, member_ids, grace_s))

    async def _drain_gang_task(self, slice_id: str,
                               node_ids: List[NodeID], grace_s: float):
        """Migration + deadline watcher for one draining slice: PG bundle
        handoff and actor migration run once for the WHOLE gang (not N
        independent per-node passes), then every member still alive at
        the shared deadline is marked dead as a planned loss."""
        member_ids = set(node_ids)
        infos = [self.nodes[nid] for nid in node_ids if nid in self.nodes]
        if not infos:
            # Same retire-or-handoff as the bottom of this task: members
            # drained while we held the _gang_tasks slot must not strand.
            leftover = [n.node_id for n in self._slice_members(slice_id)
                        if n.draining and n.node_id not in member_ids]
            if leftover:
                self._gang_tasks[slice_id] = asyncio.ensure_future(
                    self._drain_gang_task(slice_id, leftover, grace_s))
            else:
                self._gang_tasks.pop(slice_id, None)
            return
        deadline = max(n.drain_deadline for n in infos)
        # Snapshot the affected PGs at drain start, before the first
        # await: an idle member can report drain_complete within the
        # grace window and its _mark_node_dead reschedule can finish the
        # whole move before this task wakes — recovery is judged against
        # this set however the re-place ends up being driven.
        moved_pgs: List = [
            pg for pg in self.placement_groups.values()
            if pg.state != PG_REMOVED
            and member_ids & set(pg.bundle_nodes.values())]
        # Affected actors, snapshotted the same way: recovery is counted
        # at "replacement READY" — their restarted constructors DONE
        # (ACTOR_ALIVE off the gang) — not merely at PG re-commit, so
        # gang_recoveries_total and the gang_restart span reflect real
        # time-to-serve.
        moved_actors: List = [
            a for a in self.actors.values()
            if a.node_id in member_ids
            and a.state in (ACTOR_ALIVE, ACTOR_PENDING)]
        # Warm the surviving domains' worker pools BEFORE the migration
        # wave: gang recovery is bounded by the slowest actor restart,
        # and the restart is bounded by worker spawn — prestarting during
        # the grace window takes the spawn off the recovery clock.
        self._prestart_for_actors(moved_actors, member_ids)
        if grace_s > 0:
            await asyncio.sleep(min(grace_s,
                                    max(0.0, deadline - time.time())))
        t_replace = time.time()
        n_actors = 0

        async def _migrate_members(ids: set):
            # Re-place every PG with a bundle on ANY member as one unit:
            # reserve-before-release handoff (see _schedule_pg) acquires
            # the whole replacement footprint — including the slice_head
            # bundle — on the destination domain before any source
            # reservation drops. Then migrate the members' actors,
            # uncharged.
            nonlocal n_actors
            for pg in list(self.placement_groups.values()):
                if pg.state != PG_REMOVED \
                        and ids & set(pg.bundle_nodes.values()):
                    # Track every AFFECTED PG, not just the ones this
                    # scan reschedules: an idle member that reported
                    # drain_complete before the grace elapsed already
                    # kicked the reschedule via _mark_node_dead (state
                    # is RESCHEDULING by now), but its re-commit still
                    # gates gang recovery below.
                    if pg not in moved_pgs:
                        moved_pgs.append(pg)
                    if pg.state == PG_CREATED:
                        await self._reschedule_pg(pg)
            for actor in list(self.actors.values()):
                if actor.node_id in ids \
                        and actor.state in (ACTOR_ALIVE, ACTOR_PENDING):
                    n_actors += 1
                    if actor not in moved_actors:
                        moved_actors.append(actor)
                    await self._migrate_actor(
                        actor, f"slice {slice_id} draining")

        await _migrate_members(member_ids)
        self._record_gang_span(slice_id, "gang_re_place",
                               t_replace, time.time())
        # Until the shared deadline: (a) absorb LATE members — a host
        # that registered (or was drained) after this task spawned would
        # otherwise sit DRAINING forever, never migrated nor reaped —
        # and (b) watch for recovery: the replacement domain is actually
        # ready once every re-placed PG committed again. A destination
        # that never fits is the all-or-nothing fail case, left to the
        # background reschedule loop.
        t_restart = time.time()
        recovered = False
        while True:
            late = [n for n in self._slice_members(slice_id)
                    if n.draining and n.node_id not in member_ids]
            if late:
                member_ids.update(n.node_id for n in late)
                deadline = max([deadline] +
                               [n.drain_deadline for n in late])
                await _migrate_members({n.node_id for n in late})
            # Recovered = "replacement READY": every affected PG
            # re-committed OFF the gang (or removed) AND every migrated
            # actor's replacement constructor finished (ALIVE off-gang,
            # or dead for good). The non-vacuousness guard keeps the
            # counter honest: a gang with no PGs and no actors must not
            # count a "recovery" — drains==recoveries for idle slices
            # would make the ratio operators alert on meaningless.
            if not recovered and (moved_pgs or moved_actors) \
                    and self._gang_pgs_ready(moved_pgs, member_ids) \
                    and self._gang_actors_ready(moved_actors, member_ids):
                recovered = True
                self.gang_recoveries_total += 1
                self._record_gang_span(slice_id, "gang_restart",
                                       t_restart, time.time())
                logger.info("slice %s recovered: %d PG(s) re-placed, "
                            "%d actor(s) restarted uncharged",
                            slice_id, len(moved_pgs), n_actors)
            if time.time() >= deadline:
                break
            # Nothing left to watch: every member already dead and the
            # recovery verdict is in. Don't 20 Hz-poll node/PG tables
            # until the deadline for an outcome that cannot change —
            # a member drained AFTER this exits gets a fresh gang task
            # (_start_gang_drain re-spawns once the prior one is done).
            if (recovered or (not moved_pgs and not moved_actors)) \
                    and not any(
                        (n := self.nodes.get(nid)) is not None and n.alive
                        for nid in member_ids):
                break
            await asyncio.sleep(min(0.25 if recovered else 0.05,
                                    max(0.0, deadline - time.time())))
        for nid in member_ids:
            info = self.nodes.get(nid)
            if info is not None and info.alive:
                await self._mark_node_dead(
                    nid, reason=f"gang drain deadline (slice {slice_id})",
                    preempted=True)
        self._record_gang_span(slice_id, "gang_drain_window",
                               t_replace, time.time())
        if not recovered and (moved_pgs or moved_actors):
            # Replacement not READY by the drain deadline (actor restarts
            # are bounded by worker spawn + constructor time, not by the
            # reclaim notice): keep watching past it so the counter and
            # the gang_restart span still record real time-to-serve.
            watcher = asyncio.ensure_future(self._watch_gang_recovery(
                slice_id, moved_pgs, moved_actors, set(member_ids),
                t_restart))
            self._recovery_tasks.add(watcher)
            watcher.add_done_callback(self._recovery_tasks.discard)
        # Retire-or-handoff, atomically (no await in this block): a member
        # drained while the _mark_node_dead awaits above ran was past this
        # task's absorption loop, and _start_gang_drain refuses to spawn
        # while we still occupy _gang_tasks — without the handoff it would
        # sit alive+DRAINING forever (unschedulable, never migrated, never
        # reaped). Scanning and swapping in one sync block closes the race
        # with a concurrent _start_gang_drain double-spawning.
        leftover = [n.node_id for n in self._slice_members(slice_id)
                    if n.draining and n.node_id not in member_ids]
        if leftover:
            self._gang_tasks[slice_id] = asyncio.ensure_future(
                self._drain_gang_task(slice_id, leftover, grace_s))
        else:
            self._gang_tasks.pop(slice_id, None)

    @staticmethod
    def _gang_pgs_ready(moved_pgs, member_ids) -> bool:
        """Every affected PG re-committed off the gang (or removed)."""
        return all(
            pg.state == PG_REMOVED
            or (pg.state == PG_CREATED
                and not (member_ids & set(pg.bundle_nodes.values())))
            for pg in moved_pgs)

    @staticmethod
    def _gang_actors_ready(moved_actors, member_ids) -> bool:
        """Every migrated actor's replacement constructor is DONE (ALIVE
        off the gang) or the actor is gone for good — the "time-to-serve"
        half of gang recovery."""
        return all(
            a.state == ACTOR_DEAD
            or (a.state == ACTOR_ALIVE and a.node_id not in member_ids)
            for a in moved_actors)

    # Bound on the post-deadline replacement watch: a destination that
    # never fits / a constructor that never finishes gives up counting
    # (the drain itself already completed).
    RECOVERY_WATCH_S = 600.0

    async def _watch_gang_recovery(self, slice_id: str, moved_pgs,
                                   moved_actors, member_ids,
                                   t_restart: float):
        deadline = time.time() + self.RECOVERY_WATCH_S
        while time.time() < deadline:
            if self._gang_pgs_ready(moved_pgs, member_ids) \
                    and self._gang_actors_ready(moved_actors, member_ids):
                self.gang_recoveries_total += 1
                self._record_gang_span(slice_id, "gang_restart",
                                       t_restart, time.time())
                logger.info(
                    "slice %s recovered after its drain deadline: %d "
                    "PG(s) re-placed, %d actor(s) restarted uncharged",
                    slice_id, len(moved_pgs), len(moved_actors))
                return
            await asyncio.sleep(0.1)

    def _record_gang_span(self, slice_id: str, name: str,
                          start: float, end: float):
        """Flight-recorder stamp for the drain→re-place→restart window:
        rides the task-event ring as a span row, so `tracing.get_spans`
        and the state API surface gang recoveries next to task phases."""
        if not self.config.task_events_enabled:
            return
        self.task_events.append({
            "kind": "span", "trace_id": f"gang:{slice_id}",
            "span_id": os.urandom(8).hex(), "parent_id": "",
            "name": name, "task_id": f"gang:{slice_id}",
            "start": start, "end": end})

    @rpc.idempotent
    async def rpc_drain_complete(self, conn, payload):
        """Raylet-side report: running work finished / objects migrated —
        the node can die before its deadline."""
        node_id = payload["node_id"]
        info = self.nodes.get(node_id)
        if info is None or not info.draining:
            return False
        if info.alive:
            await self._mark_node_dead(node_id, reason="drained (idle)",
                                       preempted=True)
        return True

    async def _wait_node_dead(self, node_id: NodeID, timeout: float):
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return
        fut = asyncio.get_running_loop().create_future()
        self._drain_waiters.setdefault(node_id, []).append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            pass

    async def _migrate_actor(self, actor: ActorInfo, reason: str):
        """Restart an actor off a draining node WITHOUT charging its
        max_restarts budget. num_restarts still advances (callers renumber
        their seq stream per epoch); preempted_restarts records the credit.
        """
        async with self._actor_reschedule_lock:
            if actor.state == ACTOR_DEAD:
                return
            old_address = actor.address
            old_node = self.nodes.get(actor.node_id) \
                if actor.node_id is not None else None
            if old_node is not None and old_node.zone:
                # Multi-slice DCN topology awareness: the replacement
                # placement prefers a node in the SAME pod/zone as the
                # domain this actor is being drained off.
                actor.prefer_zone = old_node.zone
            actor.num_restarts += 1
            actor.preempted_restarts += 1
            actor.state = ACTOR_RESTARTING
            actor.address = ""
            self._mark_dirty()
            self.pubsub.publish("actors", {
                "event": "restarting", "actor_id": actor.actor_id,
                "actor_info": actor, "preempted": True})
        # Let the restarting event fan out before the old instance dies so
        # clients classify the RPC failures that follow as preemption.
        await asyncio.sleep(0)
        if old_address:
            try:
                await self.clients.request(
                    old_address, "kill_actor",
                    {"actor_id": actor.actor_id, "no_restart": False},
                    timeout=5.0)
            except Exception:  # noqa: BLE001 — worker may already be gone
                pass
        asyncio.ensure_future(self._schedule_actor(actor))

    async def _health_loop(self):
        cfg = self.config
        from ray_tpu.util import metrics as _metrics
        while True:
            before = time.time()
            await asyncio.sleep(cfg.heartbeat_interval_s)
            # Keep the process's metrics-reporter claim fresh — and
            # authoritative: a live GCS always owns its process's slot
            # (see metrics.claim_reporter force semantics).
            _metrics.claim_reporter(self, force=True)
            stall = time.time() - before - cfg.heartbeat_interval_s
            await self._health_tick(stall)

    async def _health_tick(self, stall: float):
        cfg = self.config
        now = time.time()
        if stall > cfg.heartbeat_interval_s:
            # The detector itself was stalled (CPU-starved head during a
            # launch storm, suspended VM, debugger): peers' heartbeats
            # were queued behind the same stall, so a stale stamp right
            # now measures OUR lag, not their death. Credit the measured
            # stall back to every live node; a genuinely dead node still
            # accrues staleness once ticks arrive on time again.
            for info in self.nodes.values():
                if info.alive:
                    info.last_heartbeat = min(
                        now, info.last_heartbeat + stall)
        for node_id, info in list(self.nodes.items()):
            if info.alive and now - info.last_heartbeat > cfg.node_death_timeout_s:
                logger.warning("node %s missed heartbeats; marking dead",
                               node_id.hex()[:12])
                # A draining node that stops heartbeating was reclaimed
                # early (notice-then-kill race): still the planned-loss
                # path, so no budgets are charged.
                await self._mark_node_dead(node_id,
                                           reason="heartbeat timeout",
                                           preempted=info.draining)

    async def _mark_node_dead(self, node_id: NodeID, reason: str,
                              preempted: bool = False):
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return
        # A node that dies mid-drain is a planned loss however the death
        # is reported (deadline watcher, raylet idle report, heartbeat
        # timeout after the VM reclaim, or a test harness hard-stop):
        # never charge budgets for it.
        preempted = preempted or info.draining
        info.alive = False
        self.node_demand.pop(node_id, None)
        task = self._drain_tasks.pop(node_id, None)
        if task is not None and task is not asyncio.current_task():
            task.cancel()
        self.pubsub.publish("nodes", {"event": "dead", "node_id": node_id,
                                      "reason": reason})
        self._mark_dirty()
        # Fail over actors that lived on that node. Planned loss (drain /
        # preemption) migrates without charging max_restarts; crash failure
        # charges as usual.
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in (ACTOR_ALIVE, ACTOR_PENDING):
                if preempted:
                    await self._migrate_actor(actor, f"node drained: {reason}")
                else:
                    await self._handle_actor_failure(actor, f"node died: {reason}")
        # Release PG bundles on that node -> reschedule.
        for pg in self.placement_groups.values():
            if pg.state == PG_CREATED and node_id in pg.bundle_nodes.values():
                asyncio.ensure_future(self._reschedule_pg(pg))
        for fut in self._drain_waiters.pop(node_id, []):
            if not fut.done():
                fut.set_result(True)

    # ------------- resource view sync (RaySyncer equivalent) -------------

    @rpc.idempotent
    async def rpc_report_resources(self, conn, payload):
        node_id = payload["node_id"]
        info = self.nodes.get(node_id)
        if info is None:
            return False
        info.resources_available = payload["available"]
        info.last_heartbeat = time.time()
        # Broadcast the delta to all raylets for local scheduling decisions.
        self._publish_resources(info)
        return True

    @rpc.idempotent
    async def rpc_get_node_address(self, conn, payload):
        """Single-node liveness + address lookup (PG-pinned lease
        routing): resolving one bundle home must not pull the O(cluster)
        get_cluster_resources payload on every cold cache / handoff
        poll."""
        n = self.nodes.get(payload["node_id"])
        if n is None:
            return None
        return {"address": n.address, "alive": n.alive,
                "draining": n.draining}

    @rpc.idempotent
    async def rpc_get_cluster_resources(self, conn, payload):
        return {
            n.node_id: {"total": n.resources_total,
                        "available": n.resources_available,
                        "alive": n.alive, "labels": n.labels,
                        "address": n.address}
            for n in self.nodes.values()
        }

    # ------------- pubsub -------------

    @rpc.idempotent
    async def rpc_subscribe(self, conn, payload):
        self.pubsub.subscribe(conn, payload["channels"])
        return True

    @rpc.non_idempotent
    async def rpc_publish(self, conn, payload):
        self.pubsub.publish(payload["channel"], payload["message"])
        return True

    # ------------- KV (function table, runtime envs, rendezvous) -------------

    @rpc.idempotent
    async def rpc_kv_put(self, conn, payload):
        """Keyed upsert: replaying never corrupts state. Caveat for the
        overwrite=False path: a replay whose first attempt inserted the
        key reports False — fine for the in-repo callers (content-
        addressed function/package export, return value ignored), but a
        claim-style user of overwrite=False can see a won claim reported
        lost after a GCS restart. Function export liveness across GCS
        restarts depends on this replay; do not flip to non_idempotent
        without giving those callers their own retry."""
        ns = self.kv.setdefault(payload.get("namespace", ""), {})
        overwrite = payload.get("overwrite", True)
        if not overwrite and payload["key"] in ns:
            return False
        ns[payload["key"]] = payload["value"]
        self._mark_dirty()
        return True

    @rpc.idempotent
    async def rpc_kv_get(self, conn, payload):
        return self.kv.get(payload.get("namespace", ""), {}).get(payload["key"])

    @rpc.idempotent
    async def rpc_kv_del(self, conn, payload):
        ns = self.kv.get(payload.get("namespace", ""), {})
        removed = ns.pop(payload["key"], None) is not None
        if removed:
            self._mark_dirty()
        return removed

    @rpc.idempotent
    async def rpc_kv_exists(self, conn, payload):
        return payload["key"] in self.kv.get(payload.get("namespace", ""), {})

    @rpc.idempotent
    async def rpc_kv_keys(self, conn, payload):
        ns = self.kv.get(payload.get("namespace", ""), {})
        prefix = payload.get("prefix", b"")
        return [k for k in ns.keys() if k.startswith(prefix)]

    # ------------- jobs -------------

    @rpc.non_idempotent
    async def rpc_register_job(self, conn, payload):
        self._job_counter += 1
        job_id = JobID.from_int(self._job_counter)
        info = JobInfo(job_id=job_id, driver_address=payload.get("driver_address", ""),
                       entrypoint=payload.get("entrypoint", ""))
        self.jobs[job_id] = info
        self._mark_dirty()
        return job_id

    @rpc.idempotent
    async def rpc_finish_job(self, conn, payload):
        info = self.jobs.get(payload["job_id"])
        if info:
            info.alive = False
            info.end_time = time.time()
        self.pubsub.publish("jobs", {"event": "finished", "job_id": payload["job_id"]})
        # Non-detached actors die with their job (reference:
        # gcs_actor_manager.h OnJobFinished); lifetime="detached" survives.
        for actor in list(self.actors.values()):
            if (actor.job_id == payload["job_id"]
                    and actor.state != ACTOR_DEAD
                    and (actor.creation_spec is None
                         or actor.creation_spec.lifetime != "detached")):
                asyncio.ensure_future(self.rpc_kill_actor(
                    None, {"actor_id": actor.actor_id, "no_restart": True}))
        self._mark_dirty()
        return True

    @rpc.idempotent
    async def rpc_get_all_jobs(self, conn, payload):
        return list(self.jobs.values())

    @rpc.idempotent
    async def rpc_owner_disconnected(self, conn, payload):
        """A core worker (driver or nested-task submitter) left the
        cluster: its non-detached actors die with it (reference:
        gcs_actor_manager.h OnWorkerDead). Raylets report this when the
        owner's lease connection closes."""
        owners = set(payload.get("owners") or [])
        for actor in list(self.actors.values()):
            if (actor.owner_address in owners
                    and actor.state != ACTOR_DEAD
                    and (actor.creation_spec is None
                         or actor.creation_spec.lifetime != "detached")):
                asyncio.ensure_future(self.rpc_kill_actor(
                    None, {"actor_id": actor.actor_id,
                           "no_restart": True}))
        return True

    # ------------- actor management -------------

    @rpc.idempotent
    async def rpc_register_actor(self, conn, payload):
        """Register + schedule an actor creation task. Idempotent: a client
        retrying after a connection loss must not double-schedule."""
        spec = payload["spec"]  # TaskSpec with is_actor_creation
        existing = self.actors.get(spec.actor_id)
        if existing is not None and existing.state != ACTOR_DEAD:
            return True
        actor = ActorInfo(
            actor_id=spec.actor_id, job_id=spec.job_id,
            name=spec.actor_name, namespace=spec.namespace,
            class_name=spec.name, max_restarts=spec.max_restarts,
            owner_address=spec.owner_address, creation_spec=spec,
            resources=dict(spec.resources),
        )
        if spec.actor_name:
            key = (spec.namespace, spec.actor_name)
            existing_id = self.named_actors.get(key)
            if existing_id is not None and \
                    self.actors[existing_id].state != ACTOR_DEAD:
                raise ValueError(
                    f"actor name '{spec.actor_name}' already taken in "
                    f"namespace '{spec.namespace}'")
            self.named_actors[key] = spec.actor_id
        self.actors[spec.actor_id] = actor
        self._mark_dirty()
        asyncio.ensure_future(self._schedule_actor(actor))
        return True

    async def _schedule_actor(self, actor: ActorInfo, delay: float = 0.0):
        """Legacy entrypoint (every (re)creation path calls it): enqueue
        into the batched creation pipeline."""
        self._enqueue_creation(actor, delay)

    def _enqueue_creation(self, actor: ActorInfo, delay: float = 0.0):
        if actor.state == ACTOR_DEAD:
            return
        self._creation_queue.append((time.time() + delay, actor))
        self._creation_wakeup.set()

    async def _actor_creation_loop(self):
        """Batched, pipelined actor creation (the launch-storm path).

        Per pass: drain every due PENDING/RESTARTING creation, place them
        ALL against one debited planning view (40 concurrent creates no
        longer pile onto the node whose availability the next heartbeat
        hasn't caught up with), send `prestart_workers` hints so the
        destination raylets fork the whole worker batch through the
        zygote before the first create lands, then fan the creates out —
        concurrently, bounded per raylet so one storm cannot saturate a
        node's RPC loop."""
        while True:
            now = time.time()
            due: List[ActorInfo] = []
            later: List[tuple] = []
            queued_ids = set()
            for ready, actor in self._creation_queue:
                if actor.state == ACTOR_DEAD:
                    continue
                if id(actor) in queued_ids:
                    # Duplicate enqueue of the same creation (e.g. a
                    # gang restart racing a retry): DROP it — deferring
                    # it would drive a second concurrent create next
                    # pass and two workers would run the constructor.
                    continue
                if ready <= now:
                    queued_ids.add(id(actor))
                    due.append(actor)
                else:
                    later.append((ready, actor))
            self._creation_queue = later
            if not due:
                self._creation_wakeup.clear()
                if later:
                    timeout = max(0.01, min(r for r, _ in later) - now)
                    try:
                        await asyncio.wait_for(
                            self._creation_wakeup.wait(), timeout)
                    except asyncio.TimeoutError:
                        pass
                else:
                    await self._creation_wakeup.wait()
                continue
            try:
                self._drive_creation_pass(due)
            except Exception:  # noqa: BLE001
                # Backstop (the pass guards per-actor internally): a bug
                # here must not kill the single cluster-wide creation
                # pipeline. Drop the pass's _creating keys before
                # re-queueing — a key registered for a create task that
                # was never spawned would make every retry a "duplicate"
                # and wedge the actor PENDING forever.
                logger.exception("creation pass failed; re-queueing "
                                 "%d creations", len(due))
                for actor in due:
                    self._creating.discard(
                        (actor.actor_id, actor.num_restarts))
                    self._enqueue_creation(actor, delay=0.5)
            # Yield so the spawned create tasks (and their RPC writes,
            # which coalesce per tick) get the loop before the next drain.
            await asyncio.sleep(0)

    def _drive_creation_pass(self, due: List[ActorInfo]):
        view = {n.node_id: dict(n.resources_available)
                for n in self.nodes.values() if self._schedulable(n)}
        assignments: List[tuple] = []
        for actor in due:
            try:
                self._place_one(actor, view, assignments)
            except Exception:  # noqa: BLE001
                # One bad entry must not abort the whole pass (the
                # already-placed actors' in-flight counts would leak and
                # the good entries would churn through re-queue).
                logger.exception("placing actor %s failed; re-queueing",
                                 actor.actor_id.hex()[:12])
                self._enqueue_creation(actor, delay=0.5)
        if not assignments:
            return
        try:
            self._send_prestart_hints([(a, n) for a, n, _k in assignments])
        except Exception:  # noqa: BLE001 — hints are best-effort
            logger.exception("prestart hints failed")
        for actor, node, key in assignments:
            asyncio.ensure_future(self._create_bounded(actor, node, key))

    def _place_one(self, actor: ActorInfo, view: dict,
                   assignments: List[tuple]):
        spec = actor.creation_spec
        if spec is None:
            return  # restored row without a spec: nothing to drive
        if actor.state not in (ACTOR_PENDING, ACTOR_RESTARTING):
            # A stale duplicate enqueue outliving the create it
            # duplicated (the in-flight guard below only spans the
            # create itself): the incarnation is already ALIVE (or
            # DEAD) — driving another create would run the constructor
            # twice and leak the first worker.
            return
        key = (actor.actor_id, actor.num_restarts)
        if key in self._creating:
            # A create for this exact incarnation is already in flight
            # (duplicate enqueues can land in different passes when their
            # delays differ): driving a second one could place it on a
            # DIFFERENT node, where the raylet's per-node (actor_id,
            # epoch) dedupe cannot join it. Drop — the in-flight create
            # re-enqueues itself on failure.
            return
        env_hash = spec.env_hash()
        env = getattr(spec, "runtime_env", None) or {}
        exact = bool(env.get("container"))
        node = self._pick_node_for(spec.resources, spec.scheduling,
                                   view=view, warm_env=env_hash,
                                   warm_exact=exact,
                                   prefer_zone=actor.prefer_zone)
        if node is None:
            # No feasible node right now; retry (autoscaler hook
            # lives here).
            self.pubsub.publish("demand",
                                {"resources": spec.resources})
            self._enqueue_creation(actor, delay=0.5)
            return
        if spec.scheduling.placement_group_id is None:
            # Debit the planning view (PG-pinned creates consume
            # bundle reservations, not node availability).
            avail = view.get(node.node_id)
            if avail is not None:
                for k, v in spec.resources.items():
                    if v > 0:
                        avail[k] = avail.get(k, 0.0) - v
        # Debit the node's synced warm-pool view too (the next
        # heartbeat restores truth): without this, every create of
        # one pass — and of the passes until that heartbeat — reads
        # the same pre-storm pool depth and piles onto one node.
        w = getattr(node, "idle_workers", None)
        if w:
            if env_hash and w.get(env_hash, 0) > 0:
                w[env_hash] -= 1
            elif not exact and w.get("", 0) > 0:
                w[""] -= 1
        self._creates_inflight[node.node_id] = \
            self._creates_inflight.get(node.node_id, 0) + 1
        self._creating.add(key)
        assignments.append((actor, node, key))

    def _send_prestart_hints(self, assignments: List[tuple]):
        """Warm the destination pools ahead of the create fan-out: one
        hint per (node, env) carrying the whole batch's demand."""
        counts: Dict[tuple, int] = {}
        addr: Dict[NodeID, str] = {}
        for actor, node in assignments:
            spec = actor.creation_spec
            env = getattr(spec, "runtime_env", None) or {}
            if env.get("container"):
                continue  # container workers need dedicated spawns
            key = (node.node_id, spec.env_hash())
            counts[key] = counts.get(key, 0) + 1
            addr[node.node_id] = node.address
        for (node_id, env_hash), count in counts.items():
            if count <= 1:
                continue  # the create itself spawns; no batch to warm
            asyncio.ensure_future(self._notify_prestart(
                addr[node_id], env_hash, count))

    async def _notify_prestart(self, address: str, env_hash: str,
                               count: int):
        try:
            conn = await self.clients.get(address)
            await conn.notify("prestart_workers",
                              {"env_hash": env_hash, "count": count})
        except Exception:  # noqa: BLE001 — a hint is best-effort
            pass

    async def _create_bounded(self, actor: ActorInfo, node: NodeInfo,
                              key: Optional[tuple] = None):
        sem = self._create_sems.get(node.node_id)
        if sem is None:
            sem = self._create_sems[node.node_id] = asyncio.Semaphore(
                max(1, int(self.config.gcs_create_actor_concurrency)))
        try:
            async with sem:
                await self._create_actor_on_node(actor, node)
        finally:
            self._creating.discard(key)
            left = self._creates_inflight.get(node.node_id, 0) - 1
            if left > 0:
                self._creates_inflight[node.node_id] = left
            else:
                self._creates_inflight.pop(node.node_id, None)

    async def _create_actor_on_node(self, actor: ActorInfo,
                                    node: NodeInfo):
        if actor.state == ACTOR_DEAD:
            return
        spec = actor.creation_spec
        try:
            result = await self.clients.request(
                node.address, "create_actor",
                {"spec": spec, "num_restarts": actor.num_restarts},
                # Must outlive the raylet's FULL create path: up to one
                # worker-start wait for a worker + another for the
                # instantiate request (compile-heavy constructors). Timing
                # out earlier respawns the create while the first still
                # progresses (thundering retries / duplicate construction).
                timeout=max(self.config.gcs_rpc_timeout_s * 4,
                            2 * self.config.worker_start_timeout_s + 30.0),
            )
        except Exception as e:
            logger.warning("actor %s creation on %s failed: %s",
                           actor.actor_id.hex()[:12], node.address, e)
            if actor.state != ACTOR_DEAD:
                self._enqueue_creation(actor, delay=0.5)
            return
        if isinstance(result, dict) and result.get("app_error"):
            # The constructor itself raised — an application error, counted
            # against max_restarts (infinite rescheduling would hang every
            # caller with a buggy __init__).
            logger.warning("actor %s constructor failed:\n%s",
                           actor.actor_id.hex()[:12], result["app_error"])
            await self._handle_actor_failure(
                actor,
                f"actor constructor raised:\n{result['app_error']}")
            return
        if actor.state == ACTOR_DEAD:
            # Killed while creation was in flight: tear the worker down so
            # its lease and resources return to the node.
            try:
                await self.clients.request(
                    result["actor_address"], "kill_actor",
                    {"actor_id": spec.actor_id, "no_restart": True},
                    timeout=5.0)
            except Exception:
                pass
            return
        actor.state = ACTOR_ALIVE
        actor.address = result["actor_address"]
        actor.worker_id = result["worker_id"]
        actor.node_id = node.node_id
        actor.prefer_zone = ""   # migration landed: the hint is spent
        self._mark_dirty()
        self._publish_actor_alive(actor)

    def _publish_actor_alive(self, actor: ActorInfo):
        """Coalesced ALIVE publish: every creation completing in the same
        loop tick rides ONE 'alive_batch' pubsub frame — a launch storm
        costs subscribers O(ticks), not O(actors)."""
        self._alive_buf.append(actor)
        if not self._alive_flush_scheduled:
            self._alive_flush_scheduled = True
            asyncio.get_running_loop().call_soon(
                self._flush_alive_publishes)

    def _flush_alive_publishes(self):
        self._alive_flush_scheduled = False
        buf, self._alive_buf = self._alive_buf, []
        # A kill/failure task may have run between the buffered publish
        # and this flush: emitting the stale ALIVE after its DEAD event
        # would resurrect the actor on clients (DEAD -> ALIVE queues
        # submitting into a killed worker).
        buf = [a for a in buf if a.state == ACTOR_ALIVE]
        if not buf:
            return
        self.alive_frames_published += 1
        if len(buf) == 1:
            self.pubsub.publish("actors", {"event": "alive",
                                           "actor_info": buf[0]})
        else:
            self.pubsub.publish("actors", {"event": "alive_batch",
                                           "actors": buf})

    def _pick_node_for(self, resources: Dict[str, float], scheduling=None,
                       view: Optional[dict] = None,
                       warm_env: Optional[str] = None,
                       warm_exact: bool = False,
                       prefer_zone: str = ""):
        """GCS-side node selection for actor creation (GcsActorScheduler).

        `view` (node_id -> available dict) is the creation pass's debited
        planning copy: batch placement decisions subtract their own
        demand instead of all reading the same heartbeat-stale
        availability. `warm_env` (an env hash, "" = no runtime env)
        routes toward warm worker capacity: among feasible nodes, ones
        holding an idle worker that can serve the env win — a storm
        spreads across the pools a prestart hint just populated instead
        of packing onto one node and cold-spawning there."""
        def avail_of(n: NodeInfo) -> Dict[str, float]:
            if view is not None:
                got = view.get(n.node_id)
                if got is not None:
                    return got
            return n.resources_available

        if scheduling is not None and scheduling.kind == "NODE_AFFINITY":
            node = self.nodes.get(scheduling.node_id)
            if node is not None and self._schedulable(node) \
                    and _fits(resources, avail_of(node)):
                return node
            if scheduling is not None and not scheduling.soft:
                return None
        if scheduling is not None and scheduling.placement_group_id is not None:
            pg = self.placement_groups.get(scheduling.placement_group_id)
            if pg is None or pg.state != PG_CREATED:
                return None
            idx = scheduling.bundle_index if scheduling.bundle_index >= 0 else 0
            node_id = pg.bundle_nodes.get(idx)
            node = self.nodes.get(node_id)
            return node if node is not None and self._schedulable(node) \
                else None
        candidates = [n for n in self.nodes.values()
                      if self._schedulable(n)
                      and _fits(resources, avail_of(n))]
        if not candidates:
            return None
        if prefer_zone:
            # Same-pod/zone replacement-domain preference (soft): a
            # migrating gang member / compiled-DAG executor lands on the
            # local DCN fabric when any matching node fits.
            same = [n for n in candidates if n.zone == prefer_zone]
            if same:
                candidates = same
        if warm_env is not None:
            def warm_cap(n: NodeInfo) -> int:
                w = getattr(n, "idle_workers", None) or {}
                # Exact (container) envs can only be served by their own
                # dedicated pool — a generic idle process cannot enter
                # the container, so fresh workers are NOT capacity here.
                cap = 0 if warm_exact else w.get("", 0)
                if warm_env:
                    cap += w.get(warm_env, 0)
                return cap
            hot = [n for n in candidates if warm_cap(n) > 0]
            if hot:
                candidates = hot
            elif self._creates_inflight:
                # Cold storm (no warm capacity anywhere, creates already
                # in flight): spread by outstanding creates per CPU so
                # every node's zygote forks its share in parallel instead
                # of one node absorbing the whole storm serially.
                return min(candidates, key=lambda n: (
                    self._creates_inflight.get(n.node_id, 0)
                    / max(1.0, n.resources_total.get("CPU", 1.0))))
        # Hybrid: prefer most-utilized node under threshold (pack), else spread.
        def util(n: NodeInfo):
            used = [
                1 - avail_of(n).get(k, 0) / t
                for k, t in n.resources_total.items() if t > 0
            ]
            return max(used) if used else 0.0
        thr = self.config.scheduler_spread_threshold
        packed = [n for n in candidates if util(n) < thr]
        if packed:
            return max(packed, key=util)
        return min(candidates, key=util)

    async def _handle_actor_failure(self, actor: ActorInfo, reason: str):
        async with self._actor_reschedule_lock:
            if actor.state == ACTOR_DEAD:
                return
            # Budget excludes preemption-caused restarts (planned node
            # loss must not consume max_restarts).
            charged = actor.num_restarts - actor.preempted_restarts
            if actor.max_restarts == -1 or charged < actor.max_restarts:
                actor.num_restarts += 1
                actor.state = ACTOR_RESTARTING
                actor.address = ""
                self.pubsub.publish("actors", {
                    "event": "restarting", "actor_id": actor.actor_id,
                    "actor_info": actor})
                self._mark_dirty()
                asyncio.ensure_future(self._schedule_actor(actor))
            else:
                actor.state = ACTOR_DEAD
                actor.death_cause = reason
                self._mark_dirty()
                self.pubsub.publish("actors", {
                    "event": "dead", "actor_id": actor.actor_id,
                    "reason": reason, "actor_info": actor})

    def _prestart_for_actors(self, actors: List[ActorInfo],
                             exclude_ids: set):
        """Hint the warm pools of every schedulable off-gang node with
        the per-env worker demand these actors are about to impose
        (ceil-split across the candidates — over-hinting decays with the
        hint TTL, under-hinting just means a cold spawn)."""
        env_counts: Dict[str, int] = {}
        for a in actors:
            spec = a.creation_spec
            if spec is None:
                continue
            env = getattr(spec, "runtime_env", None) or {}
            if env.get("container"):
                continue
            env_counts[spec.env_hash()] = \
                env_counts.get(spec.env_hash(), 0) + 1
        if not env_counts:
            return
        targets = [n for n in self.nodes.values()
                   if self._schedulable(n)
                   and n.node_id not in exclude_ids]
        if not targets:
            return
        for env_hash, count in env_counts.items():
            per = -(-count // len(targets))  # ceil split
            for n in targets:
                asyncio.ensure_future(
                    self._notify_prestart(n.address, env_hash, per))

    @rpc.idempotent
    async def rpc_prestart_workers(self, conn, payload):
        """Driver/serve-facing warm-up: fan `count` workers of demand for
        `env_hash` across the schedulable raylets (weighted by available
        CPU — the same shape placement will take) ahead of a scale-up or
        storm. Returns the number of nodes hinted."""
        count = max(0, int(payload.get("count", 0)))
        env_hash = payload.get("env_hash", "") or ""
        if count <= 0:
            return 0
        targets = [n for n in self.nodes.values() if self._schedulable(n)]
        if not targets:
            return 0
        weights = [max(0.0, n.resources_available.get("CPU", 0.0))
                   for n in targets]
        if sum(weights) <= 0:
            weights = [1.0] * len(targets)
        total_w = sum(weights)
        # Largest-remainder split: shares sum to EXACTLY count (a 1-
        # replica upscale on a 50-node cluster must hint ONE worker on
        # one node, not fork a jax-preloaded worker on all 50).
        raw = [count * w / total_w for w in weights]
        shares = [int(r) for r in raw]
        for i in sorted(range(len(targets)),
                        key=lambda i: raw[i] - shares[i],
                        reverse=True)[:count - sum(shares)]:
            shares[i] += 1
        hinted = 0
        for n, share in zip(targets, shares):
            if share <= 0:
                continue
            asyncio.ensure_future(
                self._notify_prestart(n.address, env_hash, share))
            hinted += 1
        return hinted

    @rpc.idempotent
    async def rpc_report_actor_failure(self, conn, payload):
        """Replay-safe by its own guards, and replay MATTERS: the raylet
        sends exactly one report per dead worker and swallows RpcError,
        so a report lost to a GCS restart would otherwise leave the
        actor stuck ALIVE forever. A duplicate execution is absorbed
        below — RESTARTING and stale-worker reports return early, and
        _handle_actor_failure no-ops on ACTOR_DEAD."""
        actor = self.actors.get(payload["actor_id"])
        if actor is None:
            return False
        if actor.state == ACTOR_RESTARTING:
            # Stale report about an instance the GCS already replaced (e.g.
            # the old worker of a migrated/drained actor dying on cue):
            # handling it would double-charge and double-schedule.
            return True
        wid = payload.get("worker_id")
        if (wid is not None and actor.worker_id is not None
                and wid != actor.worker_id):
            # Report names a PREVIOUS instance's worker: migration already
            # recreated the actor (warm-worker creation beats old-process
            # exit detection) — acting on it would kill the live instance.
            return True
        await self._handle_actor_failure(actor, payload.get("reason", "worker died"))
        return True

    @rpc.idempotent
    async def rpc_kill_actor(self, conn, payload):
        actor = self.actors.get(payload["actor_id"])
        if actor is None:
            return False
        no_restart = payload.get("no_restart", True)
        if no_restart:
            actor.state = ACTOR_DEAD
            actor.death_cause = "ray.kill"
            self._mark_dirty()
        if actor.name:
            key = (actor.namespace, actor.name)
            if self.named_actors.get(key) == actor.actor_id and no_restart:
                del self.named_actors[key]
        if no_restart:
            # Publish in the same synchronous run as the state write:
            # the kill RPC below can await seconds, and another handler
            # interleaving there would publish ITS transition first —
            # subscribers would see events out of order vs the state
            # they describe.
            self.pubsub.publish("actors", {"event": "dead",
                                           "actor_id": actor.actor_id,
                                           "reason": "killed",
                                           "actor_info": actor})
        if actor.address:
            try:
                await self.clients.request(
                    actor.address, "kill_actor",
                    {"actor_id": actor.actor_id, "no_restart": no_restart},
                    timeout=5.0)
            except Exception:
                pass
        return True

    @rpc.idempotent
    async def rpc_get_actor_info(self, conn, payload):
        return self.actors.get(payload["actor_id"])

    @rpc.idempotent
    async def rpc_get_named_actor(self, conn, payload):
        key = (payload.get("namespace", ""), payload["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None:
            return None
        return self.actors.get(actor_id)

    @rpc.idempotent
    async def rpc_list_named_actors(self, conn, payload):
        ns = payload.get("namespace")
        out = []
        for (namespace, name), aid in self.named_actors.items():
            if ns is None or namespace == ns:
                if self.actors[aid].state != ACTOR_DEAD:
                    out.append({"namespace": namespace, "name": name})
        return out

    @staticmethod
    def _match_filters(obj, filters) -> bool:
        """Server-side filter predicates: [(attr, op, value)] with op in
        {'=', '!='} (reference: util/state/common.py supported ops). Attr
        values compare as strings so hex ids and enums both work."""
        for attr, op, want in filters or []:
            have = obj.get(attr) if isinstance(obj, dict) \
                else getattr(obj, attr, None)
            if hasattr(have, "hex"):
                have = have.hex()
            eq = str(have) == str(want)
            if (op == "=" and not eq) or (op == "!=" and eq):
                return False
        return True

    @rpc.idempotent
    async def rpc_get_all_actors(self, conn, payload):
        filters = (payload or {}).get("filters")
        limit = (payload or {}).get("limit")
        out = [a for a in self.actors.values()
               if self._match_filters(a, filters)]
        return out[:limit] if limit else out

    # ------------- placement groups -------------

    @rpc.idempotent
    async def rpc_create_placement_group(self, conn, payload):
        """Idempotent: a client retrying after a connection loss must not
        re-register (and re-place) a PG the GCS already owns — the second
        schedule pass would race the first for reservations."""
        pg: PlacementGroupInfo = payload["pg"]
        existing = self.placement_groups.get(pg.pg_id)
        if existing is not None and existing.state != PG_REMOVED:
            return True
        self.placement_groups[pg.pg_id] = pg
        self._mark_dirty()
        asyncio.ensure_future(self._schedule_pg(pg))
        return True

    async def _schedule_pg(self, pg: PlacementGroupInfo, delay: float = 0.0):
        """Place (or re-place) a PG with reserve-before-release handoff.

        Bundles the PG already holds (`pg.bundle_nodes` surviving a node
        loss) stay reserved while the new footprint — including any
        moved bundle and the slice_head bundle of a gang — is acquired on
        the destination nodes. Only after EVERY new reservation succeeds
        does the placement commit; only after the commit are the stale
        source reservations released. A failed acquisition rolls back
        exactly what this attempt acquired (all-or-nothing), never a
        reservation the PG still owns — closing the leak where old
        reservations on surviving nodes outlived a bundle move.
        """
        if delay:
            await asyncio.sleep(delay)
        if pg.state == PG_REMOVED:
            return
        # Cancellation-proof: callers like _drain_node_task get cancelled
        # the moment their node dies (often mid-reserve — an idle raylet
        # reports drain_complete immediately). Abandoning the handoff
        # between reserve and commit/rollback is exactly how reservations
        # strand, so the critical section always runs to completion.
        await asyncio.shield(self._do_schedule_pg(pg))

    async def _do_schedule_pg(self, pg: PlacementGroupInfo):
        async with self._pg_lock:
            if pg.state == PG_REMOVED:
                return
            prev = {idx: nid for idx, nid in pg.bundle_nodes.items()
                    if (n := self.nodes.get(nid)) is not None
                    and self._schedulable(n)}
            placement = self._place_bundles(pg, prev)
            if placement is None:
                self.pubsub.publish("demand", {"pg": pg.pg_id,
                                               "bundles": pg.bundles})
                asyncio.ensure_future(self._schedule_pg(pg, delay=0.5))
                return
            # Reserve the NEW footprint in parallel (bundles staying on
            # their current node keep the reservation they already hold).
            async def _reserve(idx: int, node_id) -> bool:
                node = self.nodes.get(node_id)
                try:
                    return bool(await self.clients.request(
                        node.address, "reserve_bundle",
                        {"pg_id": pg.pg_id, "bundle_index": idx,
                         "resources": pg.bundles[idx]}, timeout=10.0))
                except Exception:  # noqa: BLE001 — node may be dying
                    return False

            items = [(idx, node_id) for idx, node_id in placement.items()
                     if prev.get(idx) != node_id]
            results = await asyncio.gather(
                *[_reserve(idx, node_id) for idx, node_id in items])

            async def _return(idx: int, node_id):
                node = self.nodes.get(node_id)
                if node is None or not node.alive:
                    return
                try:
                    await self.clients.request(
                        node.address, "return_bundle",
                        {"pg_id": pg.pg_id, "bundle_index": idx},
                        timeout=10.0)
                except Exception:  # noqa: BLE001
                    pass

            if not all(results):
                # All-or-nothing: roll back only this attempt's grabs;
                # prev reservations remain live for the retry.
                await asyncio.gather(*[
                    _return(idx, node_id)
                    for (idx, node_id), got in zip(items, results) if got])
                fails = self._pg_handoff_failures.get(pg.pg_id, 0) + 1
                self._pg_handoff_failures[pg.pg_id] = fails
                if fails >= 4 and prev:
                    # Liveness backstop: the placement-stability
                    # preference avoids self-deadlock in practice, but a
                    # plan that genuinely must cross-move bundles between
                    # surviving nodes can never be acquired while the old
                    # footprint is held. After repeated all-or-nothing
                    # failures, release the held reservations and re-place
                    # from scratch (accepting the transient window the
                    # leaky pre-handoff code always had).
                    logger.warning(
                        "pg %s handoff stuck after %d attempts; releasing "
                        "%d held reservation(s) to re-place from scratch",
                        pg.pg_id.hex()[:12], fails, len(prev))
                    await asyncio.gather(*[_return(idx, nid)
                                           for idx, nid in prev.items()])
                    pg.bundle_nodes = {}
                    self._pg_handoff_failures.pop(pg.pg_id, None)
                asyncio.ensure_future(self._schedule_pg(pg, delay=0.5))
                return
            self._pg_handoff_failures.pop(pg.pg_id, None)
            dead = [nid for nid in placement.values()
                    if (n := self.nodes.get(nid)) is None or not n.alive]
            if dead:
                # A planned home (kept bundle OR fresh reserve) died
                # during the reserve gather. Committing would pin the
                # bundle to the dead node FOREVER: _mark_node_dead's
                # reschedule scan only fires for PG_CREATED, and this PG
                # was mid-schedule when the death event ran. (The
                # pre-handoff code re-reserved every bundle per attempt,
                # so a dead node failed its reserve — skipping reserves
                # for kept bundles removed that implicit liveness check;
                # this re-check restores it.) Roll back this attempt's
                # grabs and re-place: the retry's prev-filter drops the
                # dead node.
                await asyncio.gather(*[
                    _return(idx, node_id)
                    for (idx, node_id), got in zip(items, results) if got])
                asyncio.ensure_future(self._schedule_pg(pg, delay=0.5))
                return
            if pg.state == PG_REMOVED:
                # rpc_remove_placement_group ran while the reserve gather
                # was in flight: it released the OLD bundle_nodes and
                # published "removed". Committing now would resurrect the
                # PG and strand this attempt's fresh reservations, so
                # return them instead. (No await between this check and
                # the commit below — the race cannot reopen.)
                await asyncio.gather(*[
                    _return(idx, node_id)
                    for (idx, node_id), got in zip(items, results) if got])
                return
            pg.bundle_nodes = dict(placement)
            pg.state = PG_CREATED
            self._mark_dirty()
            self.pubsub.publish("placement_groups", {"event": "created", "pg": pg})
            # Release AFTER commit: source reservations whose bundle
            # moved elsewhere (still inside the lock so a concurrent
            # reschedule cannot re-claim the key mid-release).
            stale = [(idx, nid) for idx, nid in prev.items()
                     if placement.get(idx) != nid]
            if stale:
                await asyncio.gather(*[_return(idx, nid)
                                       for idx, nid in stale])

    def _place_bundles(self, pg: PlacementGroupInfo,
                       prev: Optional[Dict[int, NodeID]] = None
                       ) -> Optional[Dict[int, NodeID]]:
        """Bundle placement honoring PACK/SPREAD/STRICT_PACK/STRICT_SPREAD.

        Reference semantics: bundle_scheduling_policy.h — STRICT_PACK all on
        one node; STRICT_SPREAD all on distinct nodes; PACK/SPREAD best-effort.

        `prev` carries the PG's live reservations (reserve-before-release
        re-placement): their capacity is credited back into the planning
        view, and each bundle PREFERS its previous node. The preference
        is load-bearing, not cosmetic — a plan that moves bundle A onto
        the node whose room is only free because bundle B's kept
        reservation "moved away" can never be reserved without releasing
        first (the handoff would deadlock against its own footprint).
        """
        alive = [n for n in self.nodes.values() if self._schedulable(n)]
        if not alive:
            return None
        prev = prev or {}
        avail = {n.node_id: dict(n.resources_available) for n in alive}
        for idx, nid in prev.items():
            pool = avail.get(nid)
            if pool is None:
                continue
            for k, v in pg.bundles[idx].items():
                if v > 0:
                    pool[k] = pool.get(k, 0.0) + v

        def prefer(order: List[NodeInfo], idx: int) -> List[NodeInfo]:
            pn = prev.get(idx)
            if pn is None:
                return order
            return ([n for n in order if n.node_id == pn]
                    + [n for n in order if n.node_id != pn])

        def bundle_order():
            # Bundles keeping a reservation place FIRST, onto their own
            # node, before homeless bundles can consume the credited
            # capacity that reservation backs (otherwise the plan
            # cross-moves and can never be acquired without releasing).
            return sorted(enumerate(pg.bundles),
                          key=lambda t: (t[0] not in prev, t[0]))

        def take(node_id, bundle) -> bool:
            a = avail[node_id]
            if all(a.get(k, 0) >= v for k, v in bundle.items()):
                for k, v in bundle.items():
                    a[k] = a.get(k, 0) - v
                return True
            return False

        placement: Dict[int, NodeID] = {}
        if pg.strategy == "STRICT_PACK":
            # Prefer the node already hosting the most of this PG's
            # reservations (re-place keeps the footprint in place).
            pref_count: Dict[NodeID, int] = {}
            for nid in prev.values():
                pref_count[nid] = pref_count.get(nid, 0) + 1
            for n in sorted(alive,
                            key=lambda n: -pref_count.get(n.node_id, 0)):
                trial = dict(avail[n.node_id])
                ok = True
                for b in pg.bundles:
                    if not all(trial.get(k, 0) >= v for k, v in b.items()):
                        ok = False
                        break
                    for k, v in b.items():
                        trial[k] = trial.get(k, 0) - v
                if ok:
                    return {i: n.node_id for i in range(len(pg.bundles))}
            return None
        if pg.strategy == "STRICT_SPREAD":
            if len(pg.bundles) > len(alive):
                return None
            used_nodes: set = set()
            for i, b in bundle_order():
                placed = False
                for n in prefer(alive, i):
                    if n.node_id in used_nodes:
                        continue
                    if take(n.node_id, b):
                        placement[i] = n.node_id
                        used_nodes.add(n.node_id)
                        placed = True
                        break
                if not placed:
                    return None
            return placement
        # PACK / SPREAD best-effort
        order = alive if pg.strategy == "PACK" else list(alive)
        for i, b in bundle_order():
            placed = False
            if pg.strategy == "SPREAD":
                # round-robin start
                order = alive[i % len(alive):] + alive[: i % len(alive)]
            for n in prefer(order, i):
                if take(n.node_id, b):
                    placement[i] = n.node_id
                    placed = True
                    break
            if not placed:
                return None
        return placement

    async def _reschedule_pg(self, pg: PlacementGroupInfo):
        pg.state = PG_PENDING
        # Bundles on dead AND draining nodes lose their placement; the
        # re-placement below only considers schedulable nodes.
        gone = {nid for nid, n in self.nodes.items()
                if not self._schedulable(n)}
        pg.bundle_nodes = {i: n for i, n in pg.bundle_nodes.items() if n not in gone}
        self.pubsub.publish("placement_groups", {"event": "rescheduling", "pg": pg})
        await self._schedule_pg(pg)

    @rpc.idempotent
    async def rpc_remove_placement_group(self, conn, payload):
        pg = self.placement_groups.get(payload["pg_id"])
        if pg is None:
            return False
        pg.state = PG_REMOVED
        # Removal ends any reserve-before-release streak; without this a
        # PG removed mid-failure-streak leaks its counter entry forever.
        self._pg_handoff_failures.pop(pg.pg_id, None)
        self._mark_dirty()
        # Publish with the state write, BEFORE the bundle-return RPCs:
        # the loop below can await tens of seconds, and the removal is
        # committed the moment the state flipped (the PG_REMOVED check
        # in _do_schedule_pg already handles a racing scheduler).
        self.pubsub.publish("placement_groups", {"event": "removed",
                                                 "pg_id": pg.pg_id})
        for idx, node_id in pg.bundle_nodes.items():
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                continue
            try:
                await self.clients.request(node.address, "return_bundle",
                                           {"pg_id": pg.pg_id, "bundle_index": idx},
                                           timeout=10.0)
            except Exception:
                pass
        return True

    @rpc.idempotent
    async def rpc_get_placement_group(self, conn, payload):
        if "pg_id" in payload and payload["pg_id"] is not None:
            return self.placement_groups.get(payload["pg_id"])
        name = payload.get("name")
        for pg in self.placement_groups.values():
            if pg.name == name and pg.state != PG_REMOVED:
                return pg
        return None

    @rpc.idempotent
    async def rpc_get_all_placement_groups(self, conn, payload):
        return list(self.placement_groups.values())

    # ------------- task events (observability) -------------

    @rpc.non_idempotent
    async def rpc_report_task_events(self, conn, payload):
        if not self.config.task_events_enabled:
            return True
        events = payload["events"]
        self.task_events.extend(events)
        overflow = len(self.task_events) - self.config.task_events_max_buffer
        if overflow > 0:
            del self.task_events[:overflow]
        return True

    @rpc.idempotent
    async def rpc_get_task_events(self, conn, payload):
        """Raw or reduced task-event query.

        `latest_only=True` collapses to the newest event per task_id
        SERVER-side before `limit` applies, so a `list_tasks(limit=10)`
        ships 10 rows over the wire instead of the whole 100k-event ring
        (satellite of the flight-recorder PR; previously every client
        query shipped the raw buffer and reduced locally). State filters
        evaluate after the reduction — filtering raw events by state
        would resurrect superseded states (a FINISHED task still has an
        old RUNNING event that would match state="RUNNING")."""
        job_id = payload.get("job_id")
        limit = payload.get("limit", 10000)
        filters = list(payload.get("filters") or [])
        state_filters = [f for f in filters if f[0] == "state"]
        other_filters = [f for f in filters if f[0] != "state"]
        if not payload.get("latest_only"):
            out = [e for e in self.task_events
                   if (job_id is None or e.get("job_id") == job_id)
                   and self._match_filters(e, filters)]
            return out[-limit:]
        latest: Dict[str, dict] = {}
        for e in self.task_events:
            if job_id is not None and e.get("job_id") != job_id:
                continue
            if e.get("kind"):  # span / serve_request rows aren't tasks —
                continue       # they'd all collapse onto task_id=None
            if not self._match_filters(e, other_filters):
                continue
            latest[e.get("task_id")] = e
        out = [e for e in latest.values()
               if self._match_filters(e, state_filters)]
        return out[-limit:]

    # ------------- persistence (GCS fault tolerance) -------------

    def snapshot(self) -> bytes:
        return pickle.dumps({
            "nodes": self.nodes, "actors": self.actors,
            "named_actors": self.named_actors, "jobs": self.jobs,
            "placement_groups": self.placement_groups, "kv": self.kv,
            "job_counter": self._job_counter,
        })

    def restore(self, data: bytes):
        state = pickle.loads(data)
        self.nodes = state["nodes"]
        self.actors = state["actors"]
        self.named_actors = state["named_actors"]
        self.jobs = state["jobs"]
        self.placement_groups = state["placement_groups"]
        self.kv = state["kv"]
        self._job_counter = state["job_counter"]

    def save_snapshot(self, path: str = "", data: bytes = None):
        path = path or self._snapshot_path()
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data if data is not None else self.snapshot())
        os.replace(tmp, path)  # atomic: restore never sees a torn snapshot


def _fits(request: Dict[str, float], available: Dict[str, float]) -> bool:
    return all(available.get(k, 0.0) >= v for k, v in request.items() if v > 0)


# Live dashboard SPA (reference capability: dashboard/ React client +
# per-module REST — here a single self-contained page served from the GCS:
# tabbed tables, a canvas task-timeline, per-worker log tail, and
# sparkline metrics built client-side from /metrics polling).
_DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:1.2rem;color:#222}
 h1{font-size:1.25rem;margin:.2rem 0 .8rem}
 nav{display:flex;gap:.4rem;margin-bottom:1rem;flex-wrap:wrap}
 nav button{border:1px solid #bbb;background:#f6f6f6;padding:.35rem .9rem;
   border-radius:.4rem;cursor:pointer;font-size:.9rem}
 nav button.active{background:#1a73e8;color:#fff;border-color:#1a73e8}
 table{border-collapse:collapse;min-width:40rem;margin-bottom:1rem}
 td,th{border:1px solid #ccc;padding:.3rem .55rem;text-align:left;
   font-size:.85rem}
 th{background:#f3f3f3} .dead{color:#b00} .ok{color:#080}
 pre{background:#0e1116;color:#cdd5e0;padding:.8rem;max-height:26rem;
   overflow:auto;font-size:.78rem;border-radius:.4rem}
 .cards{display:flex;gap:1rem;flex-wrap:wrap;margin-bottom:1rem}
 .card{border:1px solid #ddd;border-radius:.5rem;padding:.6rem .9rem;
   min-width:11rem}
 .card b{font-size:1.3rem;display:block}
 .card span{font-size:.78rem;color:#666}
 canvas.spark{display:block;margin-top:.3rem}
 #timelineC{border:1px solid #ddd;width:100%;height:420px}
 .loglist button{margin:.1rem;border:1px solid #ccc;background:#fafafa;
   padding:.2rem .5rem;border-radius:.3rem;cursor:pointer;font-size:.78rem}
 .panel{display:none}.panel.active{display:block}
</style></head><body>
<h1>ray_tpu dashboard</h1>
<nav id="tabs"></nav>
<div class="panel" id="p-overview">
 <div class="cards" id="cards"></div>
 <h2>Nodes</h2><table id="nodes"><thead><tr>
 <th>node</th><th>state</th><th>head</th><th>address</th>
 <th>CPU</th><th>TPU</th></tr></thead><tbody></tbody></table>
</div>
<div class="panel" id="p-actors">
 <table id="actors"><thead><tr>
 <th>actor</th><th>name</th><th>class</th><th>state</th><th>node</th>
 <th>restarts</th></tr></thead><tbody></tbody></table>
</div>
<div class="panel" id="p-jobs">
 <table id="jobs"><thead><tr>
 <th>job</th><th>entrypoint</th><th>state</th><th>started</th>
 <th>ended</th></tr></thead><tbody></tbody></table>
 <h2>Placement groups</h2><table id="pgs"><thead><tr>
 <th>pg</th><th>name</th><th>strategy</th><th>state</th>
 <th>bundles placed</th></tr></thead><tbody></tbody></table>
</div>
<div class="panel" id="p-tasks">
 <table id="tasks"><thead><tr>
 <th>name</th><th>state</th><th>count</th></tr></thead><tbody></tbody>
 </table>
</div>
<div class="panel" id="p-latency">
 <p style="font-size:.8rem;color:#666">Flight-recorder phase latency per
 task name (p50/p95 over the event buffer; phases per
 README&nbsp;metrics catalog).</p>
 <table id="latency"><thead><tr>
 <th>name</th><th>phase</th><th>count</th><th>p50 ms</th><th>p95 ms</th>
 </tr></thead><tbody></tbody></table>
</div>
<div class="panel" id="p-history">
 <p style="font-size:.8rem;color:#666">Server-side time series from the GCS
 tsdb (<code>/api/metrics/query</code>); one line per label set.</p>
 <select id="histName"></select>
 <select id="histFold"><option>value</option><option>rate</option>
  <option>mean</option><option>p50</option><option>p95</option>
  <option>p99</option></select>
 <select id="histWindow"><option value="60">1m</option>
  <option value="300" selected>5m</option><option value="900">15m</option>
 </select>
 <canvas id="historyC" style="border:1px solid #ddd;width:100%;
  height:300px;margin-top:.5rem"></canvas>
 <div id="histLegend" style="font-size:.78rem"></div>
</div>
<div class="panel" id="p-timeline">
 <p style="font-size:.8rem;color:#666">Completed task spans per worker
 (latest buffer; darker = FAILED).</p>
 <canvas id="timelineC"></canvas>
</div>
<div class="panel" id="p-logs">
 <div class="loglist" id="loglist"></div>
 <pre id="logview">(pick a file)</pre>
</div>
<div class="panel" id="p-metrics">
 <pre id="metrics">loading…</pre>
</div>
<script>
const TABS=[["overview","Overview"],["actors","Actors"],["jobs","Jobs/PGs"],
  ["tasks","Tasks"],["latency","Latency"],["history","History"],
  ["timeline","Timeline"],["logs","Logs"],["metrics","Metrics"]];
let active="overview", logFile=null;
const nav=document.getElementById('tabs');
for(const [id,label] of TABS){
 const b=document.createElement('button');
 b.textContent=label; b.id='tab-'+id;
 b.onclick=()=>{active=id; render(); tick();};
 nav.appendChild(b);
}
function render(){
 for(const [id] of TABS){
  document.getElementById('p-'+id).classList.toggle('active',id===active);
  document.getElementById('tab-'+id).classList.toggle('active',id===active);
 }
}
// Sparkline history built client-side from /metrics polls.
const hist={}; const HIST_N=90;
function pushHist(name,v){
 (hist[name]=hist[name]||[]).push(v);
 if(hist[name].length>HIST_N) hist[name].shift();
}
function sparkline(canvas,vals){
 const w=canvas.width=160, h=canvas.height=34;
 const g=canvas.getContext('2d'); g.clearRect(0,0,w,h);
 if(vals.length<2) return;
 const mx=Math.max(...vals), mn=Math.min(...vals), r=(mx-mn)||1;
 g.strokeStyle='#1a73e8'; g.lineWidth=1.4; g.beginPath();
 vals.forEach((v,i)=>{
  const x=i*(w-2)/(vals.length-1)+1, y=h-3-(v-mn)*(h-6)/r;
  i?g.lineTo(x,y):g.moveTo(x,y);
 });
 g.stroke();
}
function parseProm(text){
 const out={};
 for(const ln of text.split('\n')){
  if(!ln||ln.startsWith('#')) continue;
  const sp=ln.lastIndexOf(' ');
  if(sp>0){ out[ln.slice(0,sp)]=(out[ln.slice(0,sp)]||0)+
            (parseFloat(ln.slice(sp+1))||0); }
 }
 return out;
}
const CARD_METRICS=[
 ["ray_tpu_nodes_alive","nodes alive"],
 ['ray_tpu_actors{State="ALIVE"}',"actors alive"],
 ["ray_tpu_jobs_alive","jobs alive"],
 ["ray_tpu_placement_groups","placement groups"],
];
function drawCards(prom,st){
 const cards=document.getElementById('cards'); cards.innerHTML='';
 for(const [key,label] of CARD_METRICS){
  const v=prom[key]??0; pushHist(key,v);
  const d=document.createElement('div'); d.className='card';
  const b=document.createElement('b'); b.textContent=String(v);
  const s=document.createElement('span'); s.textContent=label;
  const c=document.createElement('canvas'); c.className='spark';
  d.append(b,s,c); cards.appendChild(d);
  sparkline(c,hist[key]);
 }
 const d=document.createElement('div'); d.className='card';
 const b=document.createElement('b');
 b.textContent=String(st.pending_demand);
 const s=document.createElement('span'); s.textContent='pending demand';
 d.append(b,s); cards.appendChild(d);
}
const HIST_COLORS=['#1a73e8','#d93025','#188038','#f9ab00','#9334e6',
 '#e8710a','#12b5cb','#5f6368'];
async function drawHistory(){
 const nameSel=document.getElementById('histName');
 if(!nameSel.options.length){
  const s=await (await fetch('/api/metrics/series')).json();
  for(const n of (s.names||[])){
   const o=document.createElement('option'); o.textContent=n;
   nameSel.appendChild(o);
  }
 }
 if(!nameSel.value) return;
 const fold=document.getElementById('histFold').value;
 const win=document.getElementById('histWindow').value;
 const series=await (await fetch('/api/metrics/query?name='+
   encodeURIComponent(nameSel.value)+'&fold='+fold+
   '&window='+win)).json();
 const c=document.getElementById('historyC');
 c.width=c.clientWidth; c.height=300;
 const g=c.getContext('2d'); g.clearRect(0,0,c.width,c.height);
 const pts=series.flatMap(s=>s.points||[]);
 const legend=document.getElementById('histLegend'); legend.innerHTML='';
 if(!pts.length){ g.fillStyle='#888';
   g.fillText('no samples yet',20,20); return; }
 const t0=Math.min(...pts.map(p=>p[0])), t1=Math.max(...pts.map(p=>p[0]));
 const v1=Math.max(...pts.map(p=>p[1]),0);
 const v0=Math.min(...pts.map(p=>p[1]),0);
 const ts=(t1-t0)||1, vs=(v1-v0)||1;
 g.font='11px system-ui';
 series.forEach((s,si)=>{
  const col=HIST_COLORS[si%HIST_COLORS.length];
  g.strokeStyle=col; g.lineWidth=1.4; g.beginPath();
  (s.points||[]).forEach((p,i)=>{
   const x=6+(p[0]-t0)/ts*(c.width-12);
   const y=c.height-8-(p[1]-v0)/vs*(c.height-20);
   i?g.lineTo(x,y):g.moveTo(x,y);
  });
  g.stroke();
  const d=document.createElement('span');
  d.style.color=col; d.style.marginRight='.8rem';
  d.textContent='■ '+JSON.stringify(s.tags||{});
  legend.appendChild(d);
 });
 g.fillStyle='#555';
 g.fillText(v1.toPrecision(4),6,12);
 g.fillText(v0.toPrecision(4),6,c.height-12);
}
function drawTimeline(trace){
 // Lanes draw the task slices; the full export (flow events + phase
 // sub-slices) is for chrome://tracing / Perfetto via `ray_tpu timeline`.
 trace=trace.filter(e=>e.ph==='X'&&e.cat==='task');
 const c=document.getElementById('timelineC');
 c.width=c.clientWidth; c.height=420;
 const g=c.getContext('2d'); g.clearRect(0,0,c.width,c.height);
 if(!trace.length){ g.fillStyle='#888';
   g.fillText('no completed tasks yet',20,20); return; }
 const t0=Math.min(...trace.map(e=>e.ts));
 const t1=Math.max(...trace.map(e=>e.ts+e.dur));
 const span=(t1-t0)||1;
 const lanes=[...new Set(trace.map(e=>e.pid))];
 const laneH=Math.min(26,(c.height-30)/Math.max(lanes.length,1));
 g.font='11px system-ui';
 lanes.forEach((p,i)=>{ g.fillStyle='#555';
   g.fillText(p||'driver',2,18+i*laneH); });
 for(const e of trace){
  const x=60+(e.ts-t0)/span*(c.width-70);
  const w=Math.max(2,e.dur/span*(c.width-70));
  const y=8+lanes.indexOf(e.pid)*laneH;
  g.fillStyle=e.state==='FAILED'?'#b00020':'#4a90d9';
  g.fillRect(x,y,w,laneH-6);
 }
 g.fillStyle='#555';
 g.fillText(((span)/1e6).toFixed(3)+' s span',c.width-90,c.height-6);
}
async function drawLogs(){
 const files=await (await fetch('/api/logs')).json();
 const list=document.getElementById('loglist'); list.innerHTML='';
 for(const f of files){
  const b=document.createElement('button');
  b.textContent=f.file+' ('+f.bytes+'B)';
  b.onclick=async()=>{ logFile=f.file; await tailLog(); };
  list.appendChild(b);
 }
 if(logFile) await tailLog();
}
async function tailLog(){
 const r=await (await fetch('/api/logtail?file='+
   encodeURIComponent(logFile)+'&n=300')).json();
 document.getElementById('logview').textContent=
   (r.error? 'error: '+r.error : r.lines.join('\n')) || '(empty)';
}
// All table fields are untrusted (any registrant chooses them): rows are
// built with textContent, never innerHTML.
async function fillTable(url, sel, cells, decorate){
 const rows = await (await fetch(url)).json();
 const tb = document.querySelector(sel+' tbody'); tb.innerHTML='';
 for(const row of rows){
  const tr=document.createElement('tr');
  for(const [i,v] of cells(row).entries()){
   const td=document.createElement('td');
   td.textContent=String(v);
   if(decorate) decorate(row,i,td);
   tr.appendChild(td);
  }
  tb.appendChild(tr);
 }
}
async function tick(){
 try{
  const st = await (await fetch('/api/status')).json();
  const promText = await (await fetch('/metrics')).text();
  if(active==='overview'){
   drawCards(parseProm(promText), st);
   const tb = document.querySelector('#nodes tbody'); tb.innerHTML='';
   for(const n of st.nodes){
    const avail=(r)=> (n.resources_available[r]??0)+'/'+
                      (n.resources_total[r]??0);
    const tr=document.createElement('tr');
    const cells=[n.node_id.slice(0,12), n.alive?'ALIVE':'DEAD',
                 n.is_head?'yes':'', n.address, avail('CPU'),
                 avail('TPU')];
    for(const [i,v] of cells.entries()){
     const td=document.createElement('td');
     td.textContent=String(v);
     if(i===1) td.className = n.alive?'ok':'dead';
     tr.appendChild(td);
    }
    tb.appendChild(tr);
   }
  }
  if(active==='actors') await fillTable('/api/actors', '#actors',
    a=>[a.actor_id.slice(0,12), a.name, a.class_name, a.state,
        a.node_id.slice(0,12), a.num_restarts],
    (a,i,td)=>{ if(i===3) td.className = a.state==='ALIVE'?'ok':
                (a.state==='DEAD'?'dead':''); });
  if(active==='jobs'){
   await fillTable('/api/jobs', '#jobs',
     j=>[j.job_id.slice(0,12), j.entrypoint, j.alive?'RUNNING':'FINISHED',
         new Date(j.start_time*1000).toLocaleTimeString(),
         j.end_time? new Date(j.end_time*1000).toLocaleTimeString():''],
     (j,i,td)=>{ if(i===2) td.className = j.alive?'ok':''; });
   await fillTable('/api/pgs', '#pgs',
     p=>[p.pg_id.slice(0,12), p.name, p.strategy, p.state,
         `${p.placed}/${p.bundles}`]);
  }
  if(active==='tasks') await fillTable('/api/tasks', '#tasks',
    t=>[t.name, t.state, t.count]);
  if(active==='latency') await fillTable('/api/latency', '#latency',
    r=>[r.name, r.phase, r.count, r.p50_ms, r.p95_ms]);
  if(active==='history') await drawHistory();
  if(active==='timeline')
    drawTimeline(await (await fetch('/api/timeline')).json());
  if(active==='logs') await drawLogs();
  if(active==='metrics')
    document.getElementById('metrics').textContent = promText;
 }catch(e){ /* transient poll errors: keep last view */ }
}
render(); tick(); setInterval(tick, 2000);
</script></body></html>"""
