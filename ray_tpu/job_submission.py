"""Job submission: run an entrypoint against a live cluster, with status and
log capture.

Reference parity: dashboard/modules/job/job_manager.py (:525 JobManager,
:140 JobSupervisor) + python/ray/dashboard/modules/job/sdk.py
JobSubmissionClient. TPU-first simplification: no REST hop — the client
talks straight to the GCS; the supervisor is a detached-style actor that
runs the entrypoint subprocess on a cluster node and streams its output to
a log file in the session dir.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu._private import worker_api

JOBS_NS = "job_submissions"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


def _supervisor_name(submission_id: str) -> str:
    return f"_rtpu_job_supervisor_{submission_id}"


class _JobSupervisor:
    """Actor: owns the entrypoint subprocess (JobSupervisor :140)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 gcs_address: str, session_dir: str,
                 env_vars: Optional[Dict[str, str]] = None):
        import subprocess
        import threading

        self.submission_id = submission_id
        self.entrypoint = entrypoint
        log_dir = os.path.join(session_dir or "/tmp/ray_tpu", "logs")
        os.makedirs(log_dir, exist_ok=True)
        self.log_path = os.path.join(log_dir, f"job-{submission_id}.log")
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = gcs_address
        env.update(env_vars or {})
        self._set_status(JobStatus.RUNNING)
        self._log = open(self.log_path, "wb")
        self.proc = subprocess.Popen(
            entrypoint, shell=True, stdout=self._log,
            stderr=subprocess.STDOUT, env=env)
        self._waiter = threading.Thread(target=self._wait, daemon=True)
        self._waiter.start()

    def _set_status(self, status: str, message: str = ""):
        import json
        worker_api.internal_kv_put(
            self.submission_id.encode(),
            json.dumps({"status": status, "message": message,
                        "entrypoint": self.entrypoint,
                        "log_path": self.log_path,
                        "time": time.time()}).encode(),
            namespace=JOBS_NS)

    def _wait(self):
        rc = self.proc.wait()
        self._log.flush()
        if rc == 0:
            self._set_status(JobStatus.SUCCEEDED)
        elif rc in (-15, -9):
            self._set_status(JobStatus.STOPPED, f"terminated (rc={rc})")
        else:
            self._set_status(JobStatus.FAILED, f"exit code {rc}")

    def stop(self) -> bool:
        if self.proc.poll() is None:
            self.proc.terminate()
            return True
        return False

    def logs(self) -> str:
        self._log.flush()
        try:
            with open(self.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def poll(self):
        return self.proc.poll()


class JobSubmissionClient:
    """Submit/inspect/stop jobs (reference: JobSubmissionClient SDK)."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu
        if not worker_api.is_initialized():
            ray_tpu.init(address=address)
        self._core = worker_api.get_core()

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   env_vars: Optional[Dict[str, str]] = None) -> str:
        import json

        import ray_tpu
        submission_id = submission_id or f"rtpu-{uuid.uuid4().hex[:10]}"
        worker_api.internal_kv_put(
            submission_id.encode(),
            json.dumps({"status": JobStatus.PENDING,
                        "entrypoint": entrypoint,
                        "time": time.time()}).encode(),
            namespace=JOBS_NS)
        supervisor = ray_tpu.remote(_JobSupervisor).options(
            name=_supervisor_name(submission_id), num_cpus=0).remote(
            submission_id, entrypoint, self._core.gcs_address,
            self._core.session_dir, env_vars)
        self._supervisor = supervisor
        return submission_id

    def _info(self, submission_id: str) -> dict:
        import json
        raw = worker_api.internal_kv_get(submission_id.encode(),
                                         namespace=JOBS_NS)
        if raw is None:
            raise ValueError(f"unknown job '{submission_id}'")
        return json.loads(raw)

    def get_job_status(self, submission_id: str) -> str:
        return self._info(submission_id)["status"]

    def get_job_info(self, submission_id: str) -> dict:
        return self._info(submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        import ray_tpu
        try:
            sup = ray_tpu.get_actor(_supervisor_name(submission_id))
            return ray_tpu.get(sup.logs.remote(), timeout=30)
        except ValueError:
            info = self._info(submission_id)
            path = info.get("log_path")
            if path and os.path.exists(path):
                with open(path, "rb") as f:
                    return f.read().decode(errors="replace")
            return ""

    def stop_job(self, submission_id: str) -> bool:
        import ray_tpu
        try:
            sup = ray_tpu.get_actor(_supervisor_name(submission_id))
        except ValueError:
            return False
        return ray_tpu.get(sup.stop.remote(), timeout=30)

    def list_jobs(self) -> List[dict]:
        import json
        out = []
        for key in worker_api.internal_kv_keys(namespace=JOBS_NS):
            raw = worker_api.internal_kv_get(key, namespace=JOBS_NS)
            if raw:
                info = json.loads(raw)
                info["submission_id"] = key.decode()
                out.append(info)
        return sorted(out, key=lambda i: i.get("time", 0))

    def wait_until_finish(self, submission_id: str,
                          timeout: float = 300) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.2)
        raise TimeoutError(
            f"job {submission_id} still {status} after {timeout}s")
