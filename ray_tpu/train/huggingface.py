"""TransformersTrainer: HuggingFace Trainer runs on the worker gang.

Reference parity: python/ray/train/huggingface/transformers/
(TransformersTrainer + prepare_trainer): the user's
`transformers.Trainer` training loop executes on every gang worker with
the torch.distributed gloo process group already formed (TorchConfig),
so HF's built-in DDP/distributed-sampler logic engages exactly as under
torchrun. Per-epoch metrics flow back through a report callback.
"""

from __future__ import annotations

from ray_tpu.train.torch import TorchConfig, TorchTrainer  # noqa: F401


class TransformersTrainer(TorchTrainer):
    """`TorchTrainer` whose train loop builds and runs a
    transformers.Trainer. The loop receives the train_loop_config and
    must call `trainer.train()` itself (the reference's v2 API shape:
    a plain train_loop_per_worker + prepare_trainer). The torchrun-style
    env exported by TorchConfig makes HF/accelerate engage its
    distributed (MULTI_CPU/DDP + DistributedSampler) path."""


def prepare_trainer(trainer):
    """Attach the ray_tpu report bridge to a transformers.Trainer
    (reference: ray.train.huggingface.transformers.prepare_trainer):
    every `on_log` from HF becomes a ray_tpu.train.report() so metrics
    land in Result.metrics_dataframe, and HF's own distributed setup is
    left to the already-initialized process group."""
    from transformers import TrainerCallback

    from ray_tpu.train.session import report

    class _ReportCallback(TrainerCallback):
        def on_log(self, args, state, control, logs=None, **kw):
            if logs:
                payload = {k: v for k, v in logs.items()
                           if isinstance(v, (int, float))}
                payload["step"] = state.global_step
                payload["epoch"] = float(state.epoch or 0.0)
                report(payload)

    trainer.add_callback(_ReportCallback())
    return trainer
