"""TransformersTrainer: HuggingFace Trainer runs on the worker gang.

Reference parity: python/ray/train/huggingface/transformers/
(TransformersTrainer + prepare_trainer): the user's
`transformers.Trainer` training loop executes on every gang worker with
the torch.distributed gloo process group already formed (TorchConfig),
so HF's built-in DDP/distributed-sampler logic engages exactly as under
torchrun. Per-epoch metrics flow back through a report callback.
"""

from __future__ import annotations

from typing import Callable, Optional

from ray_tpu.train.torch import TorchConfig, TorchTrainer  # noqa: F401


class TransformersTrainer(TorchTrainer):
    """HF Trainer on the gang, two construction shapes:

    1. v2 / loop shape (reference current API): pass a
       ``train_loop_per_worker`` that builds the transformers.Trainer,
       calls prepare_trainer() and .train() itself.
    2. legacy shape (reference TransformersTrainer): pass
       ``trainer_init_per_worker(train_dataset, eval_dataset, **config)``
       returning an un-run transformers.Trainer — this class wraps it in
       a loop that attaches the report bridge and runs .train(), with
       datasets forwarded per worker.

    Either way the torchrun-style env exported by TorchConfig makes
    HF/accelerate engage its distributed (MULTI_CPU/DDP +
    DistributedSampler) path.
    """

    def __init__(self, train_loop_per_worker: Optional[Callable] = None, *,
                 trainer_init_per_worker: Optional[Callable] = None,
                 datasets: Optional[dict] = None,
                 torch_config: Optional[TorchConfig] = None, **kwargs):
        if (train_loop_per_worker is None) == \
                (trainer_init_per_worker is None):
            raise ValueError(
                "pass exactly one of train_loop_per_worker or "
                "trainer_init_per_worker")
        if trainer_init_per_worker is not None:
            datasets = dict(datasets or {})
            init_fn = trainer_init_per_worker

            def train_loop_per_worker(config):
                hf_trainer = init_fn(datasets.get("train"),
                                     datasets.get("evaluation"),
                                     **(config or {}))
                prepare_trainer(hf_trainer)
                hf_trainer.train()

        super().__init__(train_loop_per_worker,
                         torch_config=torch_config, **kwargs)


def prepare_trainer(trainer):
    """Attach the ray_tpu report bridge to a transformers.Trainer
    (reference: ray.train.huggingface.transformers.prepare_trainer):
    every `on_log` from HF becomes a ray_tpu.train.report() so metrics
    land in Result.metrics_dataframe, and HF's own distributed setup is
    left to the already-initialized process group."""
    from transformers import TrainerCallback

    from ray_tpu.train.session import report

    class _ReportCallback(TrainerCallback):
        def on_log(self, args, state, control, logs=None, **kw):
            if logs:
                payload = {k: v for k, v in logs.items()
                           if isinstance(v, (int, float))}
                payload["step"] = state.global_step
                payload["epoch"] = float(state.epoch or 0.0)
                report(payload)

    trainer.add_callback(_ReportCallback())
    return trainer
