"""JaxTrainer: the DataParallelTrainer equivalent, plus Result +
checkpoint top-K bookkeeping.

Reference parity: python/ray/train/data_parallel_trainer.py:22
(DataParallelTrainer, training_loop :419), base_trainer.py:107/:561 (fit),
train/_internal/checkpoint_manager.py (top-K retention per
CheckpointConfig, air/config.py:427).

TPU-first: the per-worker train fn builds its mesh + sharded train step via
ray_tpu.parallel / ray_tpu.train.train_step; there is no DDP wrapper to
apply — the "backend" only bootstraps the JAX distributed runtime across
hosts (JaxBackendConfig). Fault tolerance is gang-granular: on failure the
whole worker group restarts from the latest checkpoint (SPMD co-failure).
"""

from __future__ import annotations

import logging
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend_executor import (BackendConfig, BackendExecutor,
                                            JaxBackendConfig,
                                            TrainingFailedError)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (CheckpointConfig, FailureConfig, RunConfig,
                                  ScalingConfig)

logger = logging.getLogger(__name__)


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    path: str = ""
    error: Optional[str] = None
    metrics_dataframe: Optional[List[Dict[str, Any]]] = None

    @property
    def best_checkpoints(self):
        return self._best_checkpoints

    _best_checkpoints: List = field(default_factory=list)


class _CheckpointBook:
    """Top-K retention (reference: CheckpointConfig.num_to_keep)."""

    def __init__(self, cfg: CheckpointConfig):
        self.cfg = cfg
        self.entries: List[tuple] = []  # (score, seq, ckpt, metrics)
        self._seq = 0

    def register(self, ckpt: Checkpoint, metrics: Dict[str, Any]):
        attr = self.cfg.checkpoint_score_attribute
        if attr is not None and attr in metrics:
            score = float(metrics[attr])
            if self.cfg.checkpoint_score_order == "min":
                score = -score
        else:
            score = float(self._seq)  # recency
        self.entries.append((score, self._seq, ckpt, dict(metrics)))
        self._seq += 1
        k = self.cfg.num_to_keep
        if k is not None and len(self.entries) > k:
            self.entries.sort(key=lambda e: (e[0], e[1]))
            evicted = self.entries.pop(0)
            self._delete(evicted[2])

    def _delete(self, ckpt: Checkpoint):
        import shutil
        try:
            shutil.rmtree(ckpt.path, ignore_errors=True)
        except Exception:
            pass

    def latest(self) -> Optional[Checkpoint]:
        if not self.entries:
            return None
        return max(self.entries, key=lambda e: e[1])[2]

    def best(self) -> Optional[Checkpoint]:
        if not self.entries:
            return None
        return max(self.entries, key=lambda e: (e[0], e[1]))[2]


class JaxTrainer:
    """Runs `train_loop_per_worker` on a gang of workers over TPU hosts.

    train_loop_per_worker() (or (config)) calls ray_tpu.train.report(...)
    once per round; rank-0 metrics become the Result rows.
    """

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 backend_config: Optional[BackendConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.train_fn = train_loop_per_worker
        self.train_config = train_loop_config
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend_config = backend_config or JaxBackendConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        if self.run_config.name is None:
            self.run_config.name = f"JaxTrainer_{int(time.time())}"
        if self.run_config.storage_path is None:
            self.run_config.storage_path = os.path.join(
                tempfile.gettempdir(), "ray_tpu_results")

    # -- data ingestion: split datasets across workers ----------------------

    def _datasets_per_worker(self) -> Optional[List[dict]]:
        if not self.datasets:
            return None
        n = self.scaling.num_workers
        per_worker: List[dict] = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                shards = ds.streaming_split(n)
            elif hasattr(ds, "split"):
                shards = ds.split(n)
            else:
                shards = [ds] * n
            for i in range(n):
                per_worker[i][name] = shards[i]
        return per_worker

    # Backstop for pathological clusters that preempt every single attempt:
    # uncharged (preemption) retries are not infinite in practice.
    _MAX_UNCHARGED_ATTEMPTS = 50

    @staticmethod
    def _failure_cause_class(err: str) -> str:
        """Best-effort failure *cause class* from a remote traceback string
        (the last line of a formatted traceback is 'Class: message')."""
        last = err.strip().splitlines()[-1] if err and err.strip() else ""
        head = last.split(":", 1)[0].strip()
        return head if head and " " not in head else "unknown"

    def fit(self) -> Result:
        failure = self.run_config.failure_config
        book = _CheckpointBook(self.run_config.checkpoint_config)
        rows: List[Dict[str, Any]] = []
        start_ckpt = self.resume_from_checkpoint
        err: Optional[str] = None
        exp_path = os.path.join(self.run_config.storage_path,
                                self.run_config.name)
        os.makedirs(exp_path, exist_ok=True)

        attempt = 0
        charged = 0   # failures counted against FailureConfig.max_failures
        while True:
            attempt += 1
            executor = BackendExecutor(
                self.scaling, self.backend_config,
                experiment_name=self.run_config.name,
                storage_path=self.run_config.storage_path,
                trial_id=f"attempt_{attempt - 1}")
            try:
                executor.start()
                executor.start_training(
                    self.train_fn, self.train_config,
                    checkpoint=book.latest() or start_ckpt,
                    datasets_per_worker=self._datasets_per_worker())
                while True:
                    round_results = executor.get_next_results()
                    if round_results is None:
                        break
                    rank0 = next((r for r in round_results
                                  if r.get("rank") == 0), round_results[0])
                    rows.append(rank0["metrics"])
                    ckpts = [r["checkpoint"] for r in round_results
                             if r.get("checkpoint") is not None]
                    if ckpts:
                        book.register(ckpts[0], rank0["metrics"])
                err = None
                break
            except TrainingFailedError as e:
                err = str(e)
                preempted = getattr(e, "preempted", False)
                charge = failure.fail_on_preemption or not preempted
                if charge:
                    charged += 1
                logger.warning(
                    "training attempt %d failed (cause=%s, %s; "
                    "%d/%s failures charged): %s",
                    attempt, self._failure_cause_class(err),
                    "charged" if charge
                    else "uncharged: preemption/drain",
                    charged,
                    failure.max_failures if failure.max_failures >= 0
                    else "inf",
                    err.splitlines()[-1] if err else "")
                out_of_budget = (failure.max_failures >= 0
                                 and charged > failure.max_failures)
                # The backstop bounds only UNCHARGED (preemption) retries;
                # charged attempts are governed solely by max_failures
                # (max_failures=-1 keeps its effectively-infinite budget).
                if out_of_budget \
                        or attempt - charged >= self._MAX_UNCHARGED_ATTEMPTS:
                    break
            finally:
                executor.shutdown()

        result = Result(metrics=rows[-1] if rows else {},
                        checkpoint=book.best() or book.latest(),
                        path=exp_path, error=err,
                        metrics_dataframe=rows)
        result._best_checkpoints = [(c, m) for _, _, c, m in
                                    sorted(book.entries, key=lambda e: e[1])]
        if err is not None:
            raise TrainingFailedError(err)
        return result
