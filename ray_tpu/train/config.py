"""Train/AIR configuration dataclasses.

Reference parity: python/ray/air/config.py (ScalingConfig :101,
FailureConfig :377, CheckpointConfig :427, RunConfig :576).

TPU-first deltas: `use_tpu`/`tpus_per_worker` instead of GPU fields, and
`placement_strategy` defaults to STRICT_PACK so a multi-worker gang lands on
one ICI domain (a slice) — the reference's PG PACK default generalized to the
TPU topology (SURVEY.md §7 "gang semantics").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class ScalingConfig:
    """How many train workers and what each reserves.

    num_workers: one worker per *host* (a TPU host owns all its local chips —
    the reference's 1-process-1-GPU assumption does not apply on TPU).
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: float = 0.0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    trainer_resources: Optional[Dict[str, float]] = None

    def worker_resources(self) -> Dict[str, float]:
        res: Dict[str, float] = {"CPU": 1.0}
        if self.resources_per_worker:
            res = {k: float(v) for k, v in self.resources_per_worker.items()}
            res.setdefault("CPU", 0.0)
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = self.tpus_per_worker or 4.0
        elif self.tpus_per_worker and "TPU" not in res:
            res["TPU"] = self.tpus_per_worker
        return res

    def as_placement_group_bundles(self):
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclass
class FailureConfig:
    """max_failures: retries of the whole training run (gang restart —
    SPMD co-failure means one worker loss restarts the mesh).

    fail_on_preemption: False (default) means gang restarts caused by a
    *planned* node loss — autoscaler drain or spot/preemptible reclaim —
    do NOT count against max_failures: the run restarts from the
    save-on-preempt checkpoint for free. Set True to charge them like any
    other failure (the pre-drain-protocol behavior).
    """

    max_failures: int = 0
    fail_on_preemption: bool = False


@dataclass
class CheckpointConfig:
    """Top-K checkpoint retention (reference: air/config.py:427)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 0
    # Tune stop criteria: {"training_iteration": N} / {metric: threshold}.
    stop: Optional[Dict[str, float]] = None
