"""SklearnTrainer: classic-ML model fitting on the cluster.

Reference parity: python/ray/train/sklearn/sklearn_trainer.py — fit an
sklearn estimator as a remote task (CPU-heavy fitting moves off the
driver), with ray_tpu.data Datasets as inputs, optional cross-validation,
and the fitted model wrapped in a Checkpoint.

Joblib-backed estimators parallelize across the cluster when combined
with `ray_tpu.util.joblib.register_ray` (the joblib backend shim).
"""

from __future__ import annotations

import os
import pickle
import tempfile
import time
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import Result

MODEL_FILE = "model.pkl"


def _dataset_to_xy(ds: Any, label_column: str):
    """Materialize a Dataset (or (X, y) tuple / dict) into numpy arrays."""
    if isinstance(ds, tuple) and len(ds) == 2:
        return np.asarray(ds[0]), np.asarray(ds[1])
    if hasattr(ds, "to_batch_columns"):
        cols = ds.to_batch_columns()
    elif hasattr(ds, "iter_batches"):
        cols: Dict[str, list] = {}
        for batch in ds.iter_batches(batch_size=4096, batch_format="numpy"):
            for k, v in batch.items():
                cols.setdefault(k, []).append(v)
        cols = {k: np.concatenate(v) for k, v in cols.items()}
    elif isinstance(ds, dict):
        cols = {k: np.asarray(v) for k, v in ds.items()}
    else:
        raise TypeError(f"unsupported dataset type {type(ds).__name__}")
    y = cols.pop(label_column)
    feats = [cols[k] for k in sorted(cols)]
    X = np.column_stack([f.reshape(len(f), -1) for f in feats])
    return X, y


@ray_tpu.remote
def _fit_remote(estimator_bytes: bytes, X, y, X_val, y_val,
                scoring_on_train: bool, fit_params: dict) -> dict:
    """Fit in a worker process; returns pickled model + metrics."""
    t0 = time.time()
    est = pickle.loads(estimator_bytes)
    est.fit(X, y, **fit_params)
    out: Dict[str, Any] = {"fit_time": time.time() - t0}
    if scoring_on_train:
        out["train_score"] = float(est.score(X, y))
    if X_val is not None:
        out["valid_score"] = float(est.score(X_val, y_val))
    out["model"] = pickle.dumps(est)
    return out


class SklearnTrainer:
    def __init__(self, *, estimator: Any, datasets: Dict[str, Any],
                 label_column: str,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 fit_params: Optional[dict] = None,
                 scoring_on_train: bool = True):
        if "train" not in datasets:
            raise ValueError("datasets must contain a 'train' entry")
        self.estimator = estimator
        self.datasets = datasets
        self.label_column = label_column
        self.scaling = scaling_config or ScalingConfig(num_workers=1)
        self.run_config = run_config or RunConfig()
        self.fit_params = fit_params or {}
        self.scoring_on_train = scoring_on_train

    def fit(self) -> Result:
        X, y = _dataset_to_xy(self.datasets["train"], self.label_column)
        X_val = y_val = None
        if "valid" in self.datasets:
            X_val, y_val = _dataset_to_xy(self.datasets["valid"],
                                          self.label_column)
        out = ray_tpu.get(_fit_remote.options(
            num_cpus=self.scaling.num_workers).remote(
                pickle.dumps(self.estimator), X, y, X_val, y_val,
                self.scoring_on_train, self.fit_params))
        model_blob = out.pop("model")
        ckpt_dir = os.path.join(
            self.run_config.storage_path or tempfile.gettempdir(),
            self.run_config.name or f"SklearnTrainer_{int(time.time())}")
        os.makedirs(ckpt_dir, exist_ok=True)
        with open(os.path.join(ckpt_dir, MODEL_FILE), "wb") as f:
            f.write(model_blob)
        ckpt = Checkpoint(ckpt_dir)
        return Result(metrics=out, checkpoint=ckpt, error=None,
                      metrics_dataframe=[out])

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        """Load the fitted estimator back from a checkpoint."""
        path = os.path.join(checkpoint.path, MODEL_FILE)
        with open(path, "rb") as f:
            return pickle.loads(f.read())
