"""TorchTrainer: distributed PyTorch training on the worker gang.

Reference parity: python/ray/train/torch/torch_trainer.py +
train/torch/config.py:64 (_setup_torch_process_group) +
train/torch/train_loop_utils.py (prepare_model/prepare_data_loader).

On this TPU-first stack the JAX path is the accelerator path; torch runs
CPU-side (aux models, preprocessing, parity workloads). The backend hook
forms a real torch.distributed gloo process group across the gang (one
rendezvous address, ranks = worker ranks), so DDP gradients all-reduce
across workers exactly as the reference's TorchTrainer does.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import cloudpickle

from ray_tpu.train.backend_executor import BackendConfig
from ray_tpu.train.trainer import JaxTrainer


@dataclass
class TorchConfig(BackendConfig):
    """Forms the torch.distributed process group over the gang
    (reference: train/torch/config.py TorchConfig)."""

    backend: str = "gloo"
    init_timeout_s: float = 60.0

    def on_start(self, executor) -> None:
        import ray_tpu
        infos = executor.node_info_per_worker
        master_addr = infos[0]["ip"]
        world = executor.world_size
        backend = self.backend
        timeout_s = self.init_timeout_s

        # Rank 0 picks a free port on ITS host so concurrent trainers
        # (e.g. parallel Tune trials on one node) never share a TCPStore
        # (reference: train/torch/config.py uses get_free_port on rank 0).
        def _free_port():
            import socket
            with socket.socket() as s:
                s.bind(("", 0))
                return s.getsockname()[1]

        master_port = ray_tpu.get(
            executor.worker_group.workers[0].execute.remote(
                cloudpickle.dumps(_free_port)), timeout=30)

        # torchrun-compatible local ranks: position among the workers
        # sharing this worker's node.
        node_of = [i["hostname"] for i in infos]
        local_rank, local_world, seen = [], [], {}
        for host in node_of:
            local_rank.append(seen.get(host, 0))
            seen[host] = seen.get(host, 0) + 1
        local_world = [seen[h] for h in node_of]

        def _init(rank, addr, port, world_size, lrank, lworld):
            import datetime
            import os

            import torch.distributed as dist
            os.environ["MASTER_ADDR"] = addr
            os.environ["MASTER_PORT"] = str(port)
            # torchrun-style env: libraries that self-configure from the
            # environment (HF accelerate picks MULTI_CPU/DDP only when
            # these are present) must see the same world the process
            # group describes.
            os.environ["RANK"] = str(rank)
            os.environ["WORLD_SIZE"] = str(world_size)
            os.environ["LOCAL_RANK"] = str(lrank)
            os.environ["LOCAL_WORLD_SIZE"] = str(lworld)
            if not dist.is_initialized():
                dist.init_process_group(
                    backend, rank=rank, world_size=world_size,
                    timeout=datetime.timedelta(seconds=timeout_s))
            return dist.get_rank()

        fn_b = cloudpickle.dumps(_init)
        refs = [w.execute.remote(fn_b, rank, master_addr, master_port,
                                 world, local_rank[rank],
                                 local_world[rank])
                for rank, w in enumerate(executor.worker_group.workers)]
        ray_tpu.get(refs, timeout=timeout_s + 60)

    def on_shutdown(self, executor) -> None:
        import ray_tpu

        def _teardown():
            import torch.distributed as dist
            if dist.is_initialized():
                dist.destroy_process_group()
            return True

        fn_b = cloudpickle.dumps(_teardown)
        try:
            refs = [w.execute.remote(fn_b)
                    for w in executor.worker_group.workers]
            ray_tpu.get(refs, timeout=30)
        except Exception:
            pass


class TorchTrainer(JaxTrainer):
    """`JaxTrainer` harness + torch process-group backend: same gang
    scheduling, fault tolerance, checkpointing, and session API; the
    train loop uses torch + torch.distributed."""

    def __init__(self, train_loop_per_worker: Callable, *,
                 torch_config: Optional[TorchConfig] = None, **kwargs):
        kwargs.setdefault("backend_config", torch_config or TorchConfig())
        super().__init__(train_loop_per_worker, **kwargs)


def prepare_model(model):
    """Wrap in DDP when the process group spans >1 worker (reference:
    train_loop_utils.py prepare_model; device move is a no-op on CPU)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel
    if dist.is_initialized() and dist.get_world_size() > 1:
        return DistributedDataParallel(model)
    return model


def prepare_data_loader(loader):
    """Re-wrap a DataLoader with a DistributedSampler so each worker sees
    its shard (reference: train_loop_utils.py prepare_data_loader).

    Shuffling follows the ORIGINAL loader (a sequential eval loader stays
    ordered). For epoch-varying shuffles call
    ``loader.sampler.set_epoch(epoch)`` each epoch, as with any
    DistributedSampler."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader, RandomSampler
    from torch.utils.data.distributed import DistributedSampler
    if not (dist.is_initialized() and dist.get_world_size() > 1):
        return loader
    if loader.batch_size is None:
        raise ValueError(
            "prepare_data_loader needs a batch_size-based DataLoader "
            "(custom batch_sampler loaders must shard themselves)")
    shuffle = isinstance(loader.sampler, RandomSampler)
    sampler = DistributedSampler(loader.dataset,
                                 num_replicas=dist.get_world_size(),
                                 rank=dist.get_rank(),
                                 shuffle=shuffle)
    return DataLoader(loader.dataset, batch_size=loader.batch_size,
                      sampler=sampler,
                      num_workers=loader.num_workers,
                      collate_fn=loader.collate_fn,
                      pin_memory=loader.pin_memory,
                      worker_init_fn=loader.worker_init_fn,
                      drop_last=loader.drop_last)
