"""Sharded train-step compilation: model + optax + ShardingStrategy -> pjit.

The TPU-native core of the Train layer: where the reference wraps a torch
module in DDP/FSDP (train/torch/train_loop_utils.py:158 prepare_model), here
a loss function and a strategy compile into ONE XLA program whose collectives
(reduce-scatter/all-gather for fsdp, all-reduce for dp, all-to-all for ep)
are inserted by GSPMD along the mesh axes. Buffer donation keeps params/opt
state in place across steps (HBM), and batch shardings put the host->device
transfer on the right devices.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import ShardingStrategy, strategy_from_name


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: Any  # scalar int32 array


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.params, s.opt_state, s.step), None),
    lambda _, c: TrainState(*c),
)


def init_train_state(init_fn: Callable[[], Any], optimizer,
                     mesh: Mesh, strategy: "ShardingStrategy | str"):
    """Initialize params + opt state directly into their shardings.

    init_fn runs under jit with sharded outputs, so even a model too big for
    one device initializes without materializing replicated copies.
    """
    if isinstance(strategy, str):
        strategy = strategy_from_name(strategy)
    with mesh:
        sample = jax.eval_shape(init_fn)
        param_sh = strategy.param_shardings(mesh, sample)
        params = jax.jit(init_fn, out_shardings=param_sh)()
        opt_state = jax.jit(
            optimizer.init,
            in_shardings=(param_sh,),
            out_shardings=_opt_state_shardings(optimizer, sample, param_sh,
                                               mesh),
        )(params)
        step = jnp.zeros((), jnp.int32)
    return TrainState(params, opt_state, step)


def _opt_state_shardings(optimizer, sample_params, param_shardings, mesh):
    """Shard optimizer moments like their parameters (ZeRO partitioning of
    optimizer state falls out of the fsdp param sharding)."""
    state_shape = jax.eval_shape(optimizer.init, sample_params)
    flat_param = [
        (tuple(str(getattr(p, "key", getattr(p, "idx", p))) for p in path), sh)
        for path, sh in jax.tree_util.tree_flatten_with_path(param_shardings)[0]
    ]

    def assign(path, leaf):
        # Moments live under e.g. (0, 'mu', <param path...>): match a param
        # whose full path is a suffix of this leaf's path.
        key = tuple(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        for pkey, sh in flat_param:
            if len(key) >= len(pkey) and key[-len(pkey):] == pkey:
                return sh
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(assign, state_shape)


def _donation_supported() -> bool:
    """Buffer donation through the axon PJRT tunnel round-trips every donated
    buffer (measured ~54x slowdown on a full train step: 136 ms -> 7.4 s on a
    v5e via the tunnel). Keep donation for real local backends, where it's
    the right call for HBM residency."""
    import os
    return not os.environ.get("PALLAS_AXON_POOL_IPS")


def make_train_step(loss_fn: Callable, optimizer, mesh: Mesh,
                    strategy: "ShardingStrategy | str",
                    sample_params: Any = None,
                    donate: Optional[bool] = None,
                    accum_steps: int = 0):
    """Build the jitted sharded train step.

    loss_fn(params, batch) -> scalar. Returns step(state, batch) ->
    (state, metrics) compiled with GSPMD shardings from the strategy.
    donate=None resolves per-platform (_donation_supported).

    accum_steps > 0: gradient accumulation INSIDE the compiled program —
    every batch leaf carries a leading [accum_steps] dim and a lax.scan
    runs that many microbatch fwd+bwd passes before ONE optimizer update.
    Besides the usual large-effective-batch use, this amortizes any
    per-dispatch transport overhead (the tunneled-chip case) across
    accum_steps of compute in a single executable launch.
    """
    if isinstance(strategy, str):
        strategy = strategy_from_name(strategy)
    if donate is None:
        donate = _donation_supported()

    def _grads(params, batch):
        return jax.value_and_grad(loss_fn)(params, batch)

    def _step(state: TrainState, batch):
        if accum_steps:
            def micro(carry, mb):
                loss_sum, gacc = carry
                loss, g = _grads(state.params, mb)
                gacc = jax.tree_util.tree_map(
                    lambda a, gi: a + gi.astype(a.dtype), gacc, g)
                return (loss_sum + loss.astype(jnp.float32), gacc), None
            gzero = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
            (loss_sum, gsum), _ = jax.lax.scan(
                micro, (jnp.float32(0.0), gzero), batch)
            inv = 1.0 / accum_steps
            grads = jax.tree_util.tree_map(lambda g: g * inv, gsum)
            loss = loss_sum * inv
        else:
            loss, grads = _grads(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return (TrainState(params, opt_state, state.step + 1),
                {"loss": loss.astype(jnp.float32), "grad_norm": gnorm,
                 "step": state.step + 1})

    bspec = strategy.batch_spec
    if accum_steps:
        bspec = P(*((None,) + tuple(bspec)))
    batch_sh = NamedSharding(mesh, bspec)
    kwargs = {}
    if donate:
        kwargs["donate_argnums"] = (0,)
    if sample_params is not None:
        param_sh = strategy.param_shardings(mesh, sample_params)
        opt_sh = _opt_state_shardings(optimizer, sample_params, param_sh, mesh)
        state_sh = TrainState(param_sh, opt_sh,
                              NamedSharding(mesh, P()))
        kwargs["in_shardings"] = (state_sh, batch_sh)
        kwargs["out_shardings"] = (state_sh, NamedSharding(mesh, P()))
    step = jax.jit(_step, **kwargs)

    # NOTE: do NOT wrap calls in `with mesh:` — an active Mesh context
    # bypasses the C++ jit dispatch fast path and re-enters Python tracing
    # machinery per call (measured 167 ms -> 6.7 s per step on a v5e).
    # Explicit NamedShardings make the context unnecessary; program-level
    # mesh use (shard_map in pipeline/ring paths) closes over the mesh
    # object directly.
    def run(state, batch):
        return step(state, batch)
    run._jitted = step
    return run


def make_eval_step(loss_fn: Callable, mesh: Mesh,
                   strategy: "ShardingStrategy | str",
                   sample_params: Any = None):
    """Jitted eval step with the strategy's batch/param shardings applied,
    so eval reuses the training layout instead of re-laying-out (replicating)
    a sharded model."""
    if isinstance(strategy, str):
        strategy = strategy_from_name(strategy)

    batch_sh = NamedSharding(mesh, strategy.batch_spec)
    kwargs = {}
    if sample_params is not None:
        param_sh = strategy.param_shardings(mesh, sample_params)
        kwargs["in_shardings"] = (param_sh, batch_sh)
        kwargs["out_shardings"] = NamedSharding(mesh, P())

    def _eval(params, batch):
        return loss_fn(params, batch).astype(jnp.float32)
    _eval = jax.jit(_eval, **kwargs)

    # No `with mesh:` on the hot path — see make_train_step.
    def run(params, batch):
        return _eval(params, batch)
    return run
