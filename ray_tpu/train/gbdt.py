"""Gradient-boosting trainers: XGBoost + LightGBM on the cluster.

Reference parity: python/ray/train/gbdt_trainer.py (shared GBDTTrainer),
train/xgboost/xgboost_trainer.py and train/lightgbm/lightgbm_trainer.py —
data-parallel boosting where each worker trains on its dataset shard and
the library's own collective (xgboost rabit / lightgbm socket machines
list) synchronizes gradients.

Neither library ships in this image, so the heavy import is gated at
fit() time with a clear error; everything around it — dataset sharding,
the worker gang, tracker/machine-list wiring, checkpointing, result
reporting — is library-independent and unit-tested through the
injectable ``train_fn_override`` seam (same pattern as the cloud
providers' injectable transports).
"""

from __future__ import annotations

import os
import pickle
import tempfile
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.sklearn import _dataset_to_xy
from ray_tpu.train.trainer import Result

MODEL_FILE = "model.pkl"


def _shard(X: np.ndarray, y: np.ndarray, rank: int, world: int):
    return X[rank::world], y[rank::world]


class GBDTTrainer:
    """Shared scaffolding (reference: train/gbdt_trainer.py).

    Subclasses define ``_default_train_fn`` — a cloudpickle-able function
    run inside each worker with
    (rank, world, X, y, X_val, y_val, params, num_boost_round, env) and
    returning {"model": bytes, ...metrics} from rank 0, {} elsewhere.
    """

    _framework = "gbdt"

    def __init__(self, *, params: Optional[dict] = None,
                 datasets: Dict[str, Any], label_column: str,
                 num_boost_round: int = 10,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 train_fn_override: Optional[Callable] = None):
        if "train" not in datasets:
            raise ValueError("datasets must contain a 'train' entry")
        self.params = dict(params or {})
        self.datasets = datasets
        self.label_column = label_column
        self.num_boost_round = num_boost_round
        self.scaling = scaling_config or ScalingConfig(num_workers=1)
        self.run_config = run_config or RunConfig()
        self._train_fn = train_fn_override or self._default_train_fn()

    # -- subclass hooks -------------------------------------------------

    def _default_train_fn(self) -> Callable:
        raise NotImplementedError

    def _coordinator_env(self, world: int) -> Dict[int, dict]:
        """Per-rank env for the library's collective (tracker address /
        machine list). Default: none (single-worker or test seam)."""
        return {r: {} for r in range(world)}

    # -- driver side ----------------------------------------------------

    def fit(self) -> Result:
        X, y = _dataset_to_xy(self.datasets["train"], self.label_column)
        X_val = y_val = None
        if "valid" in self.datasets:
            X_val, y_val = _dataset_to_xy(self.datasets["valid"],
                                          self.label_column)
        world = max(1, self.scaling.num_workers)
        envs = self._coordinator_env(world)
        import cloudpickle
        fn_blob = cloudpickle.dumps(self._train_fn)

        @ray_tpu.remote(num_cpus=1)
        def _worker(fn_blob, rank, world, Xs, ys, X_val, y_val, params,
                    rounds, env):
            import cloudpickle as cp
            return cp.loads(fn_blob)(rank, world, Xs, ys, X_val, y_val,
                                     params, rounds, env)

        t0 = time.time()
        refs = []
        for rank in range(world):
            Xs, ys = _shard(X, y, rank, world)
            refs.append(_worker.remote(fn_blob, rank, world, Xs, ys,
                                       X_val, y_val, self.params,
                                       self.num_boost_round,
                                       envs.get(rank, {})))
        outs = ray_tpu.get(refs, timeout=3600)
        metrics: Dict[str, Any] = {"fit_time": time.time() - t0,
                                   "num_workers": world}
        model_blob = None
        for out in outs:
            model_blob = out.pop("model", None) or model_blob
            metrics.update(out)
        ckpt_dir = os.path.join(
            self.run_config.storage_path or tempfile.gettempdir(),
            self.run_config.name
            or f"{type(self).__name__}_{int(time.time())}")
        os.makedirs(ckpt_dir, exist_ok=True)
        with open(os.path.join(ckpt_dir, MODEL_FILE), "wb") as f:
            f.write(model_blob or b"")
        return Result(metrics=metrics,
                      checkpoint=Checkpoint(path=ckpt_dir))

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        with open(os.path.join(checkpoint.path, MODEL_FILE), "rb") as f:
            return pickle.loads(f.read())


def _xgboost_train_fn(rank, world, X, y, X_val, y_val, params, rounds,
                      env):
    try:
        import xgboost as xgb
    except ImportError as e:  # gate: library not in this image
        raise ImportError(
            "XGBoostTrainer needs the xgboost package (not bundled in "
            "this image); pip install xgboost on every node or pass "
            "train_fn_override") from e
    import os as _os
    from contextlib import ExitStack
    with ExitStack() as stack:
        if world > 1 and env:
            for k, v in env.items():
                _os.environ[k] = str(v)
            try:  # xgboost >= 2.0
                stack.enter_context(
                    xgb.collective.CommunicatorContext(**env))
            except Exception:  # pragma: no cover - legacy rabit API
                xgb.rabit.init()
                stack.callback(xgb.rabit.finalize)
        dtrain = xgb.DMatrix(X, label=y)
        evals = []
        if X_val is not None:
            evals = [(xgb.DMatrix(X_val, label=y_val), "valid")]
        history: Dict[str, Any] = {}
        booster = xgb.train(params, dtrain, num_boost_round=rounds,
                            evals=evals, evals_result=history)
    out: Dict[str, Any] = {}
    if rank == 0:
        out["model"] = pickle.dumps(booster)
        for name, metric_hist in history.items():
            for metric, vals in metric_hist.items():
                out[f"{name}-{metric}"] = float(vals[-1])
    return out


class XGBoostTrainer(GBDTTrainer):
    """Reference: python/ray/train/xgboost/xgboost_trainer.py — each
    worker trains on its shard under a rabit/collective communicator
    started by the driver-side tracker."""

    _framework = "xgboost"

    def _default_train_fn(self):
        return _xgboost_train_fn

    def _coordinator_env(self, world: int) -> Dict[int, dict]:
        if world <= 1:
            return {0: {}}
        try:
            from xgboost.tracker import RabitTracker
        except ImportError:
            # fit() surfaces the gate from inside the worker too; here we
            # simply skip tracker setup so the error is the library one.
            return {r: {} for r in range(world)}
        tracker = RabitTracker(host_ip="127.0.0.1", n_workers=world)
        tracker.start(world)
        env = dict(tracker.worker_envs())
        env["DMLC_NUM_WORKER"] = world
        return {r: dict(env, DMLC_TASK_ID=str(r)) for r in range(world)}


def _lightgbm_train_fn(rank, world, X, y, X_val, y_val, params, rounds,
                       env):
    try:
        import lightgbm as lgb
    except ImportError as e:  # gate: library not in this image
        raise ImportError(
            "LightGBMTrainer needs the lightgbm package (not bundled in "
            "this image); pip install lightgbm on every node or pass "
            "train_fn_override") from e
    p = dict(params)
    if world > 1 and env:
        # lightgbm distributed: socket machine list + per-rank port.
        p.update(num_machines=world, machines=env["machines"],
                 local_listen_port=env["port"], tree_learner="data")
    dtrain = lgb.Dataset(X, label=y)
    valid_sets = [lgb.Dataset(X_val, label=y_val)] if X_val is not None \
        else []
    evals: Dict[str, Any] = {}
    booster = lgb.train(p, dtrain, num_boost_round=rounds,
                        valid_sets=valid_sets,
                        callbacks=[lgb.record_evaluation(evals)]
                        if valid_sets else [])
    out: Dict[str, Any] = {}
    if rank == 0:
        out["model"] = pickle.dumps(booster)
        for name, metric_hist in evals.items():
            for metric, vals in metric_hist.items():
                out[f"{name}-{metric}"] = float(vals[-1])
    return out


class LightGBMTrainer(GBDTTrainer):
    """Reference: python/ray/train/lightgbm/lightgbm_trainer.py — socket
    machine-list data-parallel training."""

    _framework = "lightgbm"

    def _default_train_fn(self):
        return _lightgbm_train_fn

    def _coordinator_env(self, world: int) -> Dict[int, dict]:
        if world <= 1:
            return {0: {}}
        base = 52000 + (os.getpid() % 500) * 4
        machines = ",".join(f"127.0.0.1:{base + r}" for r in range(world))
        return {r: {"machines": machines, "port": base + r}
                for r in range(world)}
