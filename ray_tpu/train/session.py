"""Per-worker training session: report/get_checkpoint/get_context.

Reference parity: python/ray/train/_internal/session.py (_TrainSession :109,
report :662, get_checkpoint :749) — the worker side of the Train control
plane. The hot loop (the jitted train step) never touches this; report() is
called once per logging interval with scalar metrics.

report() blocks until the driver consumes the result — that per-round
synchronization is what keeps N SPMD workers in lockstep with the driver's
bookkeeping, replacing the reference's queue+next_results pairing
(train/_internal/backend_executor.py:541).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclass
class TrainContext:
    world_size: int = 1
    world_rank: int = 0
    local_rank: int = 0
    local_world_size: int = 1
    node_rank: int = 0
    experiment_name: str = ""
    storage_path: str = ""
    trial_id: str = ""

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_id(self) -> str:
        return self.trial_id

    def get_storage_path(self) -> str:
        return self.storage_path


class _Session:
    """Lives inside the train-worker actor; bridges the user's train fn
    (running on an executor thread) and the driver's polling."""

    def __init__(self, context: TrainContext,
                 checkpoint: Optional[Checkpoint] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self.context = context
        self.starting_checkpoint = checkpoint
        self.datasets = datasets or {}
        self._results: "queue.Queue" = queue.Queue(maxsize=1)
        self._stop = threading.Event()
        # Save-on-preempt: set by TrainWorker.request_save (driver push) or
        # implied by a drain notice for this worker's node; cleared when a
        # checkpoint is reported.
        self._save_requested = threading.Event()

    # -- called from the user train fn (executor thread) --

    def should_checkpoint(self) -> bool:
        """True when the training loop should save NOW: this worker's host
        received a drain/preemption notice (or the driver requested an
        immediate save). A loop that checkpoints every N steps should also
        checkpoint when this flips, so the post-preemption restart resumes
        from the current step instead of the last periodic save."""
        if self._save_requested.is_set():
            return True
        try:
            from ray_tpu._private import worker_api
            return worker_api.local_node_draining()
        except Exception:  # noqa: BLE001 — outside a worker process
            return False

    def request_save(self):
        self._save_requested.set()

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None):
        if self._stop.is_set():
            raise _StopTraining()
        if checkpoint is not None:
            self._save_requested.clear()
        self._results.put({"type": "report", "metrics": dict(metrics),
                           "checkpoint": checkpoint,
                           "rank": self.context.world_rank})
        # Block until consumed: put the *next* item only after the driver
        # drains; queue(maxsize=1) already provides that.

    def finish(self, value: Any = None, error: Optional[str] = None):
        self._results.put({"type": "error", "error": error}
                          if error else {"type": "done", "value": value})

    # -- called from the actor's RPC threads --

    def next_result(self, timeout: float = 10.0) -> Optional[dict]:
        try:
            return self._results.get(timeout=timeout)
        except queue.Empty:
            return None

    def stop(self):
        self._stop.set()


class _StopTraining(Exception):
    pass


_session: Optional[_Session] = None


def _set_session(s: Optional[_Session]):
    global _session
    _session = s


def _get_session() -> Optional[_Session]:
    return _session


def get_context() -> TrainContext:
    if _session is None:
        return TrainContext()
    return _session.context


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (+ optional checkpoint) for this round; blocks until
    the driver has consumed the previous round (lockstep backpressure)."""
    if _session is None:
        raise RuntimeError("train.report() called outside a train worker")
    _session.report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    if _session is None:
        return None
    return _session.starting_checkpoint


def should_checkpoint() -> bool:
    """Save-on-preempt hook: True when this worker's node is being drained
    (spot reclaim / downscale) and the loop should checkpoint immediately.
    Always False outside a train worker."""
    if _session is None:
        return False
    return _session.should_checkpoint()


def get_dataset_shard(name: str = "train"):
    if _session is None:
        raise RuntimeError("get_dataset_shard() outside a train worker")
    ds = _session.datasets.get(name)
    if ds is None:
        raise KeyError(f"no dataset shard named '{name}'")
    return ds
