"""Checkpoint: a directory of files, plus sharded-pytree save/restore.

Reference parity: python/ray/train/_checkpoint.py (directory on a
filesystem) + train/_internal/storage.py (StorageContext upload path).

TPU-first: `save_pytree`/`load_pytree` write one .npz per host of
*addressable* shards only, so a fully-sharded (fsdp) model checkpoints in
parallel across hosts with no gather — the orbax/tensorstore layout idea
with a dependency-free implementation. Restore re-shards onto the current
mesh via jax.device_put (resharding across topologies falls out of GSPMD
shardings rather than a resharding tool).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import uuid
from contextlib import contextmanager
from typing import Any, Dict, Optional

import numpy as np


class Checkpoint:
    """A reference to a directory of checkpoint data."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        with open(os.path.join(d, "_dict.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    def to_dict(self) -> Dict[str, Any]:
        p = os.path.join(self.path, "_dict.pkl")
        if not os.path.exists(p):
            raise ValueError(f"checkpoint at {self.path} has no dict payload")
        with open(p, "rb") as f:
            return pickle.load(f)

    def to_directory(self, path: Optional[str] = None) -> str:
        dst = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        if os.path.abspath(dst) != self.path:
            shutil.copytree(self.path, dst, dirs_exist_ok=True)
        return dst

    @contextmanager
    def as_directory(self):
        yield self.path

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


# ---------------------------------------------------------------------------
# Sharded pytree persistence (host-parallel, addressable shards only).
# ---------------------------------------------------------------------------

def _flatten(tree):
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def save_pytree(tree: Any, directory: str, *, name: str = "state",
                process_index: Optional[int] = None) -> None:
    """Write the addressable shards of a (possibly sharded) pytree.

    Layout: <dir>/<name>.treedef.pkl (host 0), <dir>/<name>.h<proc>.npz with
    one entry per (leaf, shard) this host can address, plus a JSON index of
    global shapes/dtypes for restore-time validation.
    """
    import jax

    os.makedirs(directory, exist_ok=True)
    leaves, treedef = _flatten(tree)
    proc = jax.process_index() if process_index is None else process_index

    arrays: Dict[str, np.ndarray] = {}
    index = {"leaves": [], "name": name}
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array):
            index["leaves"].append({
                "i": i, "shape": list(leaf.shape), "dtype": str(leaf.dtype)})
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue  # one copy per shard across replicas
                idx = _slice_key(shard.index, leaf.shape)
                arrays[f"{i}|{idx}"] = np.asarray(shard.data)
        else:
            index["leaves"].append({"i": i, "py": True})
            if proc == 0:
                arrays[f"{i}|py"] = np.frombuffer(
                    pickle.dumps(leaf), dtype=np.uint8)
    np.savez(os.path.join(directory, f"{name}.h{proc}.npz"), **arrays)
    if proc == 0:
        with open(os.path.join(directory, f"{name}.treedef.pkl"), "wb") as f:
            pickle.dump(treedef, f)
        with open(os.path.join(directory, f"{name}.index.json"), "w") as f:
            json.dump(index, f)


def _slice_key(index, shape) -> str:
    parts = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        parts.append(f"{start}:{stop}")
    return ",".join(parts)


def _parse_slice_key(key: str):
    if not key:
        return ()
    out = []
    for part in key.split(","):
        a, b = part.split(":")
        out.append(slice(int(a), int(b)))
    return tuple(out)


def load_pytree(directory: str, *, name: str = "state",
                shardings: Any = None) -> Any:
    """Restore a pytree saved by save_pytree.

    shardings: optional pytree of NamedSharding to place leaves onto (may be
    a different mesh/layout than at save time). Without it, leaves load as
    host numpy arrays.
    """
    import jax

    with open(os.path.join(directory, f"{name}.treedef.pkl"), "rb") as f:
        treedef = pickle.load(f)
    with open(os.path.join(directory, f"{name}.index.json")) as f:
        index = json.load(f)

    shards: Dict[int, list] = {}
    pyleaves: Dict[int, Any] = {}
    for fn in sorted(os.listdir(directory)):
        if not (fn.startswith(f"{name}.h") and fn.endswith(".npz")):
            continue
        with np.load(os.path.join(directory, fn)) as z:
            for key in z.files:
                si, idx = key.split("|", 1)
                i = int(si)
                if idx == "py":
                    pyleaves[i] = pickle.loads(z[key].tobytes())
                else:
                    shards.setdefault(i, []).append((idx, z[key]))

    leaves = []
    sh_leaves = None
    if shardings is not None:
        # flatten_up_to keeps None placeholders aligned with saved leaves
        sh_leaves = treedef.flatten_up_to(shardings)
    for meta in index["leaves"]:
        i = meta["i"]
        if meta.get("py"):
            leaves.append(pyleaves[i])
            continue
        full = np.empty(tuple(meta["shape"]), dtype=np.dtype(meta["dtype"]))
        for idx, arr in shards.get(i, []):
            full[_parse_slice_key(idx)] = arr
        if sh_leaves is not None and sh_leaves[i] is not None:
            leaves.append(jax.device_put(full, sh_leaves[i]))
        else:
            leaves.append(full)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def new_checkpoint_dir(storage_path: str, run_name: str, step: int) -> str:
    d = os.path.join(storage_path, run_name,
                     f"checkpoint_{step:06d}_{uuid.uuid4().hex[:6]}")
    os.makedirs(d, exist_ok=True)
    return d
