"""ray_tpu.train: the TPU-native Train layer.

Sharded train-step compilation (train_step), the worker-gang harness
(JaxTrainer/BackendExecutor/WorkerGroup), the per-worker session API
(report/get_checkpoint/get_context), and host-parallel sharded
checkpointing (Checkpoint, save_pytree/load_pytree).
"""

from ray_tpu.train.checkpoint import (Checkpoint, load_pytree,
                                      new_checkpoint_dir, save_pytree)
from ray_tpu.train.config import (CheckpointConfig, FailureConfig, RunConfig,
                                  ScalingConfig)
from ray_tpu.train.session import (get_checkpoint, get_context,
                                   get_dataset_shard, report,
                                   should_checkpoint, TrainContext)
from ray_tpu.train.train_step import (TrainState, init_train_state,
                                      make_eval_step, make_train_step)
from ray_tpu.train.trainer import JaxTrainer, Result
from ray_tpu.train.backend_executor import (BackendConfig, BackendExecutor,
                                            JaxBackendConfig,
                                            TrainingFailedError)
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.train.sklearn import SklearnTrainer
from ray_tpu.train.gbdt import (GBDTTrainer, LightGBMTrainer,
                                XGBoostTrainer)
from ray_tpu.train.tensorflow import (TensorflowConfig, TensorflowTrainer,
                                      build_tf_config)
from ray_tpu.train.torch import (TorchConfig, TorchTrainer, prepare_model,
                                 prepare_data_loader)
from ray_tpu.train.huggingface import TransformersTrainer, prepare_trainer

__all__ = [
    "Checkpoint", "save_pytree", "load_pytree", "new_checkpoint_dir",
    "ScalingConfig", "RunConfig", "FailureConfig", "CheckpointConfig",
    "report", "get_checkpoint", "get_context", "get_dataset_shard",
    "should_checkpoint",
    "TrainContext", "TrainState", "init_train_state", "make_train_step",
    "make_eval_step", "JaxTrainer", "Result", "BackendConfig",
    "JaxBackendConfig", "BackendExecutor", "WorkerGroup",
    "TrainingFailedError", "SklearnTrainer", "TorchTrainer",
    "TensorflowTrainer", "TensorflowConfig", "build_tf_config",
    "TorchConfig", "prepare_model", "prepare_data_loader",
    "TransformersTrainer", "prepare_trainer",
]
