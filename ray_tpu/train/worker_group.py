"""WorkerGroup: a gang of train-worker actors on a placement group.

Reference parity: python/ray/train/_internal/worker_group.py +
backend_executor.py:197 (PG creation) / :347 (rank mapping).

TPU-first: bundles are per-host gangs (a worker owns every chip of its
host), placed STRICT_PACK onto one slice when the resources fit — the ICI
domain is the scheduling unit (SURVEY.md §7).
"""

from __future__ import annotations

import socket
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.train import session as _session_mod
from ray_tpu.train.session import TrainContext, _Session
from ray_tpu.util.placement_group import placement_group, \
    remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


class TrainWorker:
    """Actor hosting one training process (one host's worth of chips)."""

    def __init__(self):
        self._session: Optional[_Session] = None
        self._thread: Optional[threading.Thread] = None

    def node_info(self) -> Dict[str, Any]:
        import os
        return {"hostname": socket.gethostname(), "pid": os.getpid(),
                "ip": "127.0.0.1",
                "node_id": os.environ.get("RAY_TPU_NODE_ID", "")}

    def set_env(self, env: Dict[str, str]) -> None:
        import os
        os.environ.update(env)

    def start_run(self, fn_bytes: bytes, config: Optional[dict],
                  context: TrainContext,
                  checkpoint=None, datasets: Optional[dict] = None) -> None:
        fn = cloudpickle.loads(fn_bytes)
        sess = _Session(context, checkpoint=checkpoint, datasets=datasets)
        self._session = sess
        _session_mod._set_session(sess)

        def _target():
            try:
                if config is not None:
                    out = fn(config)
                else:
                    out = fn()
                sess.finish(out)
            except _session_mod._StopTraining:
                sess.finish(None)
            except BaseException:  # noqa: BLE001
                sess.finish(None, error=traceback.format_exc())

        t = threading.Thread(target=_target, daemon=True,
                             name="train_loop")
        self._thread = t
        t.start()

    def poll(self, timeout: float = 10.0) -> Optional[dict]:
        if self._session is None:
            return {"type": "error", "error": "worker not started"}
        out = self._session.next_result(timeout)
        if out is not None and out["type"] in ("done", "error"):
            _session_mod._set_session(None)
        return out

    def interrupt(self) -> None:
        if self._session is not None:
            self._session.stop()

    def request_save(self) -> None:
        """Driver-side save-on-preempt push: the next report should carry
        a checkpoint (session.should_checkpoint() flips true)."""
        if self._session is not None:
            self._session.request_save()

    def execute(self, fn_bytes: bytes, *args, **kwargs):
        """Run an arbitrary fn inline on the worker (setup/teardown path)."""
        fn = cloudpickle.loads(fn_bytes)
        return fn(*args, **kwargs)


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK",
                 max_concurrency: int = 4):
        self.num_workers = num_workers
        self._pg = placement_group(
            [dict(resources_per_worker) for _ in range(num_workers)],
            strategy=placement_strategy)
        if not self._pg.wait(120.0):
            remove_placement_group(self._pg)
            raise TimeoutError(
                f"placement group for {num_workers} train workers "
                f"({resources_per_worker} each) not placeable")
        cls = ray_tpu.remote(TrainWorker)
        self.workers = []
        for i in range(num_workers):
            w = cls.options(
                num_cpus=resources_per_worker.get("CPU", 1),
                resources={k: v for k, v in resources_per_worker.items()
                           if k != "CPU"} or None,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self._pg,
                    placement_group_bundle_index=i),
                max_concurrency=max_concurrency,
            ).remote()
            self.workers.append(w)

    def execute(self, fn: Callable, *args, timeout: Optional[float] = 60,
                **kwargs) -> List[Any]:
        """Run fn(*args) on every worker, gather results (barrier)."""
        fn_b = cloudpickle.dumps(fn)
        refs = [w.execute.remote(fn_b, *args, **kwargs) for w in self.workers]
        return ray_tpu.get(refs, timeout=timeout)

    def node_infos(self) -> List[Dict[str, Any]]:
        return ray_tpu.get([w.node_info.remote() for w in self.workers],
                           timeout=60)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self._pg)
        except Exception:
            pass
        self.workers = []
