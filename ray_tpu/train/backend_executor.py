"""BackendExecutor: drives a WorkerGroup through a training run.

Reference parity: python/ray/train/_internal/backend_executor.py
(BackendExecutor :65, PG creation :197, rank mapping :347,
get_next_results :541) and train/torch/config.py:64 (_setup_torch_process
group) — here the backend hook configures the JAX distributed runtime
(coordinator rendezvous over the GCS-backed collective layer) instead of a
NCCL/TCP process group; in-program collectives are compiled by XLA and need
no runtime object at all (SURVEY.md §2.5).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class BackendConfig:
    """Base backend config; subclass hooks run on start/shutdown."""

    def on_start(self, executor: "BackendExecutor") -> None:  # noqa: D401
        pass

    def on_shutdown(self, executor: "BackendExecutor") -> None:
        pass


@dataclass
class JaxBackendConfig(BackendConfig):
    """Sets up the JAX distributed runtime across hosts when needed.

    distributed='auto': initialize jax.distributed only when >1 node hosts
    workers AND a TPU platform is present. On a single host (or CPU tests)
    each worker keeps its private local backend.
    """

    distributed: str = "auto"
    coordinator_port: int = 7311

    def on_start(self, executor: "BackendExecutor") -> None:
        infos = executor.node_info_per_worker
        n_nodes = len({i["hostname"] for i in infos})
        if self.distributed == "off":
            return
        if self.distributed == "auto" and n_nodes <= 1:
            return
        coord = f"{infos[0]['ip']}:{self.coordinator_port}"
        world = executor.world_size

        def _init(coord_addr, num_procs, rank):
            import jax
            jax.distributed.initialize(
                coordinator_address=coord_addr, num_processes=num_procs,
                process_id=rank)

        fn_b = cloudpickle.dumps(_init)
        import ray_tpu
        refs = [
            w.execute.remote(fn_b, coord, world, rank)
            for rank, w in enumerate(executor.worker_group.workers)
        ]
        ray_tpu.get(refs, timeout=120)


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, scaling: ScalingConfig,
                 backend: Optional[BackendConfig] = None,
                 experiment_name: str = "", storage_path: str = "",
                 trial_id: str = ""):
        self.scaling = scaling
        self.backend = backend or JaxBackendConfig()
        self.experiment_name = experiment_name
        self.storage_path = storage_path
        self.trial_id = trial_id
        self.worker_group: Optional[WorkerGroup] = None
        self.node_info_per_worker: List[dict] = []
        self.world_size = scaling.num_workers

    def start(self):
        self.worker_group = WorkerGroup(
            self.scaling.num_workers, self.scaling.worker_resources(),
            self.scaling.placement_strategy)
        self.node_info_per_worker = self.worker_group.node_infos()
        self.backend.on_start(self)

    def _contexts(self) -> List[TrainContext]:
        """Global rank = position; local rank = index within its node
        (reference rank mapping: backend_executor.py:347)."""
        by_node: Dict[str, List[int]] = {}
        for i, info in enumerate(self.node_info_per_worker):
            by_node.setdefault(info["hostname"], []).append(i)
        node_order = sorted(by_node)
        ctxs = []
        for rank, info in enumerate(self.node_info_per_worker):
            host = info["hostname"]
            ctxs.append(TrainContext(
                world_size=self.world_size, world_rank=rank,
                local_rank=by_node[host].index(rank),
                local_world_size=len(by_node[host]),
                node_rank=node_order.index(host),
                experiment_name=self.experiment_name,
                storage_path=self.storage_path, trial_id=self.trial_id))
        return ctxs

    def start_training(self, train_fn: Callable, config: Optional[dict],
                       checkpoint: Optional[Checkpoint] = None,
                       datasets_per_worker: Optional[List[dict]] = None):
        fn_b = cloudpickle.dumps(train_fn)
        refs = []
        for i, (w, ctx) in enumerate(zip(self.worker_group.workers,
                                         self._contexts())):
            ds = datasets_per_worker[i] if datasets_per_worker else None
            refs.append(w.start_run.remote(fn_b, config, ctx,
                                           checkpoint, ds))
        import ray_tpu
        ray_tpu.get(refs, timeout=60)

    def get_next_results(self, timeout: float = 600.0) -> Optional[List[dict]]:
        """One result per worker for this round, or None when all done.

        Raises TrainingFailedError if any worker errored.
        """
        import ray_tpu
        deadline = time.monotonic() + timeout
        results: List[Optional[dict]] = [None] * len(self.worker_group.workers)
        pending = set(range(len(results)))
        finished: Dict[int, dict] = {}
        while pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError("timed out waiting for train results")
            refs = {i: self.worker_group.workers[i].poll.remote(
                min(5.0, remaining)) for i in pending}
            for i, ref in refs.items():
                out = ray_tpu.get(ref, timeout=30)
                if out is None:
                    continue
                if out["type"] == "error":
                    self._interrupt()
                    raise TrainingFailedError(out["error"])
                if out["type"] == "done":
                    finished[i] = out
                    pending.discard(i)
                else:
                    results[i] = out
                    pending.discard(i)
        if finished and len(finished) == len(results):
            return None
        if finished:
            # Mixed done/report: treat stragglers' reports as the last round.
            return [r for r in results if r is not None] or None
        return results

    def _interrupt(self):
        for w in self.worker_group.workers:
            try:
                w.interrupt.remote()
            except Exception:
                pass

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self)
            self.worker_group.shutdown()
            self.worker_group = None
