"""BackendExecutor: drives a WorkerGroup through a training run.

Reference parity: python/ray/train/_internal/backend_executor.py
(BackendExecutor :65, PG creation :197, rank mapping :347,
get_next_results :541) and train/torch/config.py:64 (_setup_torch_process
group) — here the backend hook configures the JAX distributed runtime
(coordinator rendezvous over the GCS-backed collective layer) instead of a
NCCL/TCP process group; in-program collectives are compiled by XLA and need
no runtime object at all (SURVEY.md §2.5).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class BackendConfig:
    """Base backend config; subclass hooks run on start/shutdown."""

    def on_start(self, executor: "BackendExecutor") -> None:  # noqa: D401
        pass

    def on_shutdown(self, executor: "BackendExecutor") -> None:
        pass


@dataclass
class JaxBackendConfig(BackendConfig):
    """Sets up the JAX distributed runtime across hosts when needed.

    distributed='auto': initialize jax.distributed only when >1 node hosts
    workers. On a single host (or CPU tests) each worker keeps its private
    local backend. distributed='force': ALWAYS form the multi-controller
    gang — the real multi-host path (one process per host, global device
    list spanning every process) — even when the worker processes share a
    host, which is how CI proves multi-process correctness without
    multi-host hardware (reference: backend_executor.py:347 rank mapping +
    train/torch/config.py:64 process-group bootstrap).

    platform='cpu' (tests): each worker process binds
    `local_device_count` virtual CPU devices and cross-process
    collectives run over gloo; '' leaves the worker's platform alone
    (TPU workers own their host's chips natively).
    """

    distributed: str = "auto"  # auto | off | force
    coordinator_port: int = 0  # 0 = pick a free port on worker 0
    platform: str = ""
    local_device_count: int = 0

    def on_start(self, executor: "BackendExecutor") -> None:
        infos = executor.node_info_per_worker
        n_nodes = len({i["hostname"] for i in infos})
        if self.distributed == "off":
            return
        if self.distributed == "auto" and n_nodes <= 1:
            return
        from ray_tpu.parallel.mp_check import free_port, init_process
        port = self.coordinator_port
        if not port:
            # The coordinator binds on WORKER 0's host, so the free-port
            # probe must run there — a driver-side probe checks the wrong
            # machine on real multi-host clusters.
            w0 = executor.worker_group.workers[0]
            import ray_tpu as _rt
            port = _rt.get(w0.execute.remote(cloudpickle.dumps(free_port)),
                           timeout=60)
        coord = f"{infos[0]['ip']}:{port}"
        world = executor.world_size
        fn_b = cloudpickle.dumps(init_process)
        import ray_tpu
        refs = [
            w.execute.remote(fn_b, rank, world, coord,
                             self.local_device_count, self.platform)
            for rank, w in enumerate(executor.worker_group.workers)
        ]
        ray_tpu.get(refs, timeout=180)


class TrainingFailedError(RuntimeError):
    """A training attempt failed. ``preempted`` marks attempts lost to a
    planned node drain / spot reclaim: JaxTrainer retries those without
    charging FailureConfig.max_failures (unless fail_on_preemption)."""

    def __init__(self, *args, preempted: bool = False):
        self.preempted = preempted
        super().__init__(*args)


class BackendExecutor:
    def __init__(self, scaling: ScalingConfig,
                 backend: Optional[BackendConfig] = None,
                 experiment_name: str = "", storage_path: str = "",
                 trial_id: str = ""):
        self.scaling = scaling
        self.backend = backend or JaxBackendConfig()
        self.experiment_name = experiment_name
        self.storage_path = storage_path
        self.trial_id = trial_id
        self.worker_group: Optional[WorkerGroup] = None
        self.node_info_per_worker: List[dict] = []
        self.world_size = scaling.num_workers

    def start(self):
        self._started_at = time.time()
        self._save_pushed = False
        self.worker_group = WorkerGroup(
            self.scaling.num_workers, self.scaling.worker_resources(),
            self.scaling.placement_strategy)
        self.node_info_per_worker = self.worker_group.node_infos()
        self.backend.on_start(self)
        self._start_preempt_watcher()

    # ---- driver-side preemption watcher ----

    def _start_preempt_watcher(self):
        """Event-driven watch of the driver's drain-event log so
        save-on-preempt fires even when only the DRIVER sees the notice
        (e.g. the gang workers' pubsub frames were lost with their node,
        or the notice landed between report rounds). Worker-side
        should_checkpoint() and the get_next_results() check remain the
        other two braces.

        The core worker's nodes-channel pubsub pushes a wakeup the
        instant a notice lands (worker_api.add_drain_event_listener), so
        steady state costs zero polls; a slow poll remains as the
        fallback for a dropped subscription (no core, or the GCS channel
        lost mid-run). Without a subscription the legacy 0.25 s poll
        cadence is kept."""
        self._stop_preempt_watcher()  # restart attempts re-arm cleanly
        self._watch_stop = threading.Event()
        kick = self._watch_kick = threading.Event()
        from ray_tpu._private import worker_api

        def _listener():
            kick.set()

        self._watch_listener = _listener
        try:
            subscribed = worker_api.add_drain_event_listener(_listener)
        except Exception:  # noqa: BLE001 — not connected (unit tests)
            subscribed = False
        poll_s = 5.0 if subscribed else 0.25

        def _loop():
            while not self._watch_stop.is_set():
                kick.wait(poll_s)  # push wakeup; timeout = poll fallback
                kick.clear()
                if self._watch_stop.is_set() or self._save_pushed:
                    return
                try:
                    if self._preempted_since_start():
                        self._save_pushed = True
                        self.request_save()
                        return
                except Exception:  # noqa: BLE001 — watcher must not die
                    pass

        self._watcher = threading.Thread(
            target=_loop, daemon=True, name="train-preempt-watcher")
        self._watcher.start()

    def _stop_preempt_watcher(self):
        stop = getattr(self, "_watch_stop", None)
        if stop is not None:
            stop.set()
        kick = getattr(self, "_watch_kick", None)
        if kick is not None:
            kick.set()  # unblock the wait so the thread exits promptly
        listener = getattr(self, "_watch_listener", None)
        if listener is not None:
            from ray_tpu._private import worker_api
            try:
                worker_api.remove_drain_event_listener(listener)
            except Exception:  # noqa: BLE001
                pass
            self._watch_listener = None
        watcher = getattr(self, "_watcher", None)
        if watcher is not None:
            watcher.join(timeout=2.0)
            self._watcher = None

    def _preempted_since_start(self) -> bool:
        """Did a node HOSTING THIS GANG receive a drain/preemption notice
        after this attempt started? Gang failures observed afterwards
        classify as planned loss (the SPMD gang co-fails with its slowest
        host, so a single drained host explains the whole restart).
        Events for unrelated nodes (routine downscales elsewhere) must
        not launder genuine crashes into uncharged retries."""
        from ray_tpu._private import worker_api
        try:
            events = worker_api.drain_events()
        except Exception:  # noqa: BLE001 — not connected (unit tests)
            return False
        start = getattr(self, "_started_at", 0.0)
        gang_nodes = {i.get("node_id", "") for i in self.node_info_per_worker}
        gang_nodes.discard("")
        def _hexes(ev) -> list:
            ids = ev.get("node_ids") or [ev.get("node_id")]
            return [nid.hex() if hasattr(nid, "hex") else str(nid or "")
                    for nid in ids]

        for ev in events:
            if ev.get("time", 0.0) < start:
                continue
            # Unknown gang placement (old workers without node_id): keep
            # the permissive classification rather than charging a
            # possibly-planned loss. Slice gang_draining events carry
            # every member id — any overlap with the training gang's
            # hosts classifies the restart as planned.
            if not gang_nodes or gang_nodes & set(_hexes(ev)):
                return True
        return False

    def request_save(self):
        """Best-effort save-on-preempt push to every gang worker."""
        for w in self.worker_group.workers if self.worker_group else []:
            try:
                w.request_save.remote()
            except Exception:  # noqa: BLE001 — worker may be mid-restart
                pass

    def _contexts(self) -> List[TrainContext]:
        """Global rank = position; local rank = index within its node
        (reference rank mapping: backend_executor.py:347)."""
        by_node: Dict[str, List[int]] = {}
        for i, info in enumerate(self.node_info_per_worker):
            by_node.setdefault(info["hostname"], []).append(i)
        node_order = sorted(by_node)
        ctxs = []
        for rank, info in enumerate(self.node_info_per_worker):
            host = info["hostname"]
            ctxs.append(TrainContext(
                world_size=self.world_size, world_rank=rank,
                local_rank=by_node[host].index(rank),
                local_world_size=len(by_node[host]),
                node_rank=node_order.index(host),
                experiment_name=self.experiment_name,
                storage_path=self.storage_path, trial_id=self.trial_id))
        return ctxs

    def start_training(self, train_fn: Callable, config: Optional[dict],
                       checkpoint: Optional[Checkpoint] = None,
                       datasets_per_worker: Optional[List[dict]] = None):
        fn_b = cloudpickle.dumps(train_fn)
        refs = []
        for i, (w, ctx) in enumerate(zip(self.worker_group.workers,
                                         self._contexts())):
            ds = datasets_per_worker[i] if datasets_per_worker else None
            refs.append(w.start_run.remote(fn_b, config, ctx,
                                           checkpoint, ds))
        import ray_tpu
        ray_tpu.get(refs, timeout=60)

    def get_next_results(self, timeout: float = 600.0) -> Optional[List[dict]]:
        """One result per worker for this round, or None when all done.

        Raises TrainingFailedError if any worker errored.
        """
        import ray_tpu
        deadline = time.monotonic() + timeout
        results: List[Optional[dict]] = [None] * len(self.worker_group.workers)
        pending = set(range(len(results)))
        finished: Dict[int, dict] = {}
        # Driver-side save-on-preempt push: if a gang node's drain notice
        # reached the driver (it may land here before the workers see
        # their own pubsub), tell every worker to checkpoint on its next
        # report. Belt to the worker-side should_checkpoint() braces.
        if not self._save_pushed and self._preempted_since_start():
            self._save_pushed = True
            self.request_save()
        while pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError("timed out waiting for train results")
            refs = {i: self.worker_group.workers[i].poll.remote(
                min(5.0, remaining)) for i in pending}
            for i, ref in refs.items():
                try:
                    out = ray_tpu.get(ref, timeout=30)
                except Exception as e:  # noqa: BLE001 — gang worker lost
                    self._interrupt()
                    raise TrainingFailedError(
                        f"{type(e).__name__}: {e}",
                        preempted=(getattr(e, "preempted", False)
                                   or self._preempted_since_start()))
                if out is None:
                    continue
                if out["type"] == "error":
                    self._interrupt()
                    raise TrainingFailedError(
                        out["error"],
                        preempted=self._preempted_since_start())
                if out["type"] == "done":
                    finished[i] = out
                    pending.discard(i)
                else:
                    results[i] = out
                    pending.discard(i)
        if finished and len(finished) == len(results):
            return None
        if finished:
            # Mixed done/report: treat stragglers' reports as the last round.
            return [r for r in results if r is not None] or None
        return results

    def _interrupt(self):
        for w in self.worker_group.workers:
            try:
                w.interrupt.remote()
            except Exception:
                pass

    def shutdown(self):
        self._stop_preempt_watcher()
        if self.worker_group is not None:
            self.backend.on_shutdown(self)
            self.worker_group.shutdown()
            self.worker_group = None
