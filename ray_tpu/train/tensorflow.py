"""TensorflowTrainer: multi-worker TF training on the worker gang.

Reference parity: python/ray/train/tensorflow/tensorflow_trainer.py +
train/tensorflow/config.py (_setup_tensorflow_environment). TensorFlow's
MultiWorkerMirroredStrategy self-configures from the TF_CONFIG env var —
the backend's only job is to assemble the cluster spec (every worker's
host:port plus this worker's task index) and export it on each gang
member before the user's train loop runs.

tensorflow itself is NOT imported here: it is only needed inside the
user's train_loop_per_worker (this image does not bundle TF; the trainer
degrades to a clear ImportError in the loop, same as the reference on a
TF-less cluster).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import cloudpickle

from ray_tpu.train.backend_executor import BackendConfig
from ray_tpu.train.trainer import JaxTrainer


def build_tf_config(workers: List[Tuple[str, int]], rank: int) -> str:
    """TF_CONFIG JSON for one worker (pure; reference:
    train/tensorflow/config.py _setup_tensorflow_environment)."""
    if not 0 <= rank < len(workers):
        raise ValueError(f"rank {rank} out of range for "
                         f"{len(workers)} workers")
    return json.dumps({
        "cluster": {"worker": [f"{ip}:{port}" for ip, port in workers]},
        "task": {"type": "worker", "index": rank},
    })


@dataclass
class TensorflowConfig(BackendConfig):
    """Exports TF_CONFIG across the gang so MultiWorkerMirroredStrategy
    forms its collective ring over the workers."""

    init_timeout_s: float = 60.0

    def on_start(self, executor) -> None:
        import ray_tpu
        infos = executor.node_info_per_worker

        def _free_port():
            import socket
            with socket.socket() as s:
                s.bind(("", 0))
                return s.getsockname()[1]

        ports = executor.worker_group.execute(_free_port, timeout=30)
        workers = [(info["ip"], port)
                   for info, port in zip(infos, ports)]

        def _export(rank, workers):
            import os
            os.environ["TF_CONFIG"] = build_tf_config(workers, rank)
            return True

        fn_b = cloudpickle.dumps(_export)
        refs = [w.execute.remote(fn_b, rank, workers)
                for rank, w in enumerate(executor.worker_group.workers)]
        ray_tpu.get(refs, timeout=self.init_timeout_s)

    def on_shutdown(self, executor) -> None:
        def _clear():
            import os
            os.environ.pop("TF_CONFIG", None)
            return True

        try:
            executor.worker_group.execute(_clear, timeout=30)
        except Exception:
            pass


class TensorflowTrainer(JaxTrainer):
    """`JaxTrainer` gang harness + TF_CONFIG backend: the user's loop
    builds `tf.distribute.MultiWorkerMirroredStrategy()` which reads the
    exported cluster spec (reference: tensorflow_trainer.py)."""

    def __init__(self, train_loop_per_worker: Callable, *,
                 tensorflow_config: Optional[TensorflowConfig] = None,
                 **kwargs):
        kwargs.setdefault("backend_config",
                          tensorflow_config or TensorflowConfig())
        super().__init__(train_loop_per_worker, **kwargs)
