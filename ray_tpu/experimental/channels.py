"""Multi-slot shared-memory ring channels: the compiled-DAG data plane.

Grown out of `experimental/channel.py` (single-slot seqlock mutable
object; reference: python/ray/experimental/channel.py:49 over
src/ray/core_worker/experimental_mutable_object_manager.h). A
`RingChannel` is a bounded ring of seqlock slots in ONE shared-memory
segment:

  * **bounded depth** — `depth` slots means `depth` pipelined ticks can
    be in flight; the writer blocks when the slowest reader is `depth`
    messages behind (natural backpressure, no unbounded buffering);
  * **single writer, multi reader** — each reader owns a cursor slot in
    the segment header, so N consumers of one producer progress
    independently and the writer's window is bounded by the SLOWEST;
  * **per-slot seqlock discipline** — every slot carries its own
    [version, length] header; version `2*seq+1` marks a write in
    flight, `2*seq+2` a completed write of message `seq`. Readers
    re-check BOTH fields after the copy and treat an unpicklable
    payload under a stable header as torn (bounded retries), exactly
    the PR 7 torn-read discipline;
  * **pickle-5 out-of-band payloads** — values are serialized with the
    framework `SerializationContext` (same wire layout as the object
    store), so numpy / host jax arrays land as out-of-band buffers
    written straight into the slot and deserialize as ZERO-COPY views
    onto the shared memory;
  * **oversize + cross-node fallback** — a message that exceeds the
    slot capacity ships as an object-store reference (`worker_api.put`)
    with only the tiny ref crossing the ring, so the payload rides the
    existing store transfer path (`store_fetch_remote` pulls it on a
    remote node). A fully cross-node EDGE uses `StoreChannel`, which
    runs the same protocol over the GCS KV + object store so a
    compiled DAG can span raylets.

Zero-copy caveat: a value read from a ring slot references the shared
memory of that slot, which the writer reuses once every reader is
`depth` messages past it — consume (or copy) the value before reading
`depth` further messages. The compiled-DAG run loop consumes each value
within its tick, so this never bites there.

Segment names are `rtch_<creator-pid>_<rand>`; readers parse the
creator pid for a liveness backstop (creator process gone + segment
still mapped = orphaned pipeline: reads raise ChannelClosedError
instead of spinning forever).
"""

from __future__ import annotations

import os
import pickle
import struct
import time
from typing import Any, List, Optional

from ray_tpu._private.object_store import Arena, _attach_untracked
from ray_tpu.experimental.channel import ChannelClosedError

__all__ = ["RingChannel", "RingReader", "RingWriter", "StoreChannel",
           "StoreReader", "ChannelClosedError", "ChannelDataLostError",
           "local_segments"]

MAGIC = 0x52544348  # "RTCH"
_HEADER = struct.Struct("<IIQQQQ")   # magic, closed, depth, slot, n_readers, seq
HEADER_SIZE = 64                     # _HEADER.size padded to a cache line
_SLOT_HEADER = struct.Struct("<QQ")  # version, length

_SEQ_OFF = 4 + 4 + 8 + 8 + 8         # byte offset of writer_seq in the header
_CLOSED_OFF = 4                      # byte offset of the closed flag


def _align(n: int, a: int = 64) -> int:
    return (n + a - 1) // a * a


def local_segments(prefix: str = "rtch_") -> List[str]:
    """Names of live /dev/shm segments with `prefix` (teardown asserts)."""
    try:
        return sorted(n for n in os.listdir("/dev/shm")
                      if n.startswith(prefix))
    except OSError:
        return []


def _serialization_ctx():
    from ray_tpu._private.serialization import context_for_process
    return context_for_process()


_full_counter = None


def _note_channel_full() -> None:
    """Count a write that had to block on a full channel (backpressure
    engaging is normal; a high rate means the pipeline is depth-bound)."""
    global _full_counter
    if _full_counter is None:
        from ray_tpu.util import metrics
        _full_counter = metrics.Counter(
            "ray_tpu_dag_channel_full_total",
            "compiled-DAG channel writes that blocked on a full ring")
    _full_counter.inc()


class ChannelDataLostError(Exception):
    """An oversize payload's object is gone and no recovery re-sealed the
    record: the writer that owned it died before (or without) a recovery
    pass replaying its cached wire bytes. Typed so a compiled-DAG tick
    fails fast instead of hanging a full object-get timeout."""


class _OversizeRef:
    """Marker for a payload that exceeded the slot: only the object-store
    ref crosses the ring; the value rides the store (transfer) path."""

    __slots__ = ("ref",)

    def __init__(self, ref):
        self.ref = ref


def _resolve_payload(value):
    if isinstance(value, _OversizeRef):
        from ray_tpu._private import worker_api
        # Ring endpoints share a node, so the payload sits in the local
        # object plane: pin it straight from the store (zero-copy view,
        # no owner round trip). The full get is only the fallback for a
        # not-yet-sealed put racing the read.
        try:
            hit = worker_api.get_local(value.ref)
        except Exception:  # noqa: BLE001 — fall through to the full get
            hit = None
        if hit is not None:
            return hit[0]
        return worker_api.get(value.ref, timeout=60)
    return value


class _RingBase:
    """Layout math + attach shared by creator/writer/reader handles."""

    def __init__(self, depth: int, slot_size: int, n_readers: int):
        self.depth = int(depth)
        self.slot_size = int(slot_size)
        self.n_readers = int(n_readers)
        self._cursor_off = HEADER_SIZE
        self._slots_off = _align(HEADER_SIZE + 8 * self.n_readers)
        self._slot_stride = _align(_SLOT_HEADER.size + self.slot_size)
        self.total_size = self._slots_off + self.depth * self._slot_stride
        self._buf = None
        self.name = ""

    # -- header accessors ---------------------------------------------
    def _writer_seq(self) -> int:
        return struct.unpack_from("<Q", self._buf, _SEQ_OFF)[0]

    def _set_writer_seq(self, seq: int) -> None:
        struct.pack_into("<Q", self._buf, _SEQ_OFF, seq)

    def closed(self) -> bool:
        return struct.unpack_from("<I", self._buf, _CLOSED_OFF)[0] != 0

    def close(self) -> None:
        """Mark the channel closed: blocked readers AND writers wake with
        ChannelClosedError on their next spin. Idempotent, any-process."""
        try:
            struct.pack_into("<I", self._buf, _CLOSED_OFF, 1)
        except (ValueError, TypeError):
            pass  # segment already torn down

    def reopen(self) -> None:
        """Clear the closed flag so a SURVIVING segment can carry traffic
        again after a recovery pass quiesced it. Contents, the writer
        seq, and every reader cursor are preserved — in-flight messages
        that were in the ring when the channel closed are still
        delivered. Only call once every attached loop has observed the
        close and exited (compiled-DAG recovery awaits the loop refs
        first); reopening under a live reader would race its drain."""
        try:
            struct.pack_into("<I", self._buf, _CLOSED_OFF, 0)
        except (ValueError, TypeError):
            pass  # segment already torn down

    def _cursor(self, idx: int) -> int:
        return struct.unpack_from("<Q", self._buf,
                                  self._cursor_off + 8 * idx)[0]

    def _set_cursor(self, idx: int, v: int) -> None:
        struct.pack_into("<Q", self._buf, self._cursor_off + 8 * idx, v)

    def _min_cursor(self) -> int:
        off = self._cursor_off
        buf = self._buf
        return min(struct.unpack_from("<Q", buf, off + 8 * i)[0]
                   for i in range(self.n_readers))

    def _slot_view(self, seq: int):
        base = self._slots_off + (seq % self.depth) * self._slot_stride
        return base

    # -- liveness backstop --------------------------------------------
    def _creator_alive(self) -> bool:
        """False once the creating process is gone AND the segment file
        was unlinked (or the creator pid no longer exists): a reader
        blocked on an orphaned pipeline must error out, not spin."""
        if not os.path.isdir("/dev/shm"):
            return True  # non-Linux: no cheap check; rely on close()
        if not os.path.exists(f"/dev/shm/{self.name}"):
            return False
        try:
            pid = int(self.name.split("_")[1])
        except (IndexError, ValueError):
            return True
        return os.path.exists(f"/proc/{pid}")


class RingChannel(_RingBase):
    """Creator-side channel object (driver). Owns the segment lifetime;
    hand `writer()` to the producer and `reader(i)` to each consumer."""

    def __init__(self, slot_size: int = 1 << 20, depth: int = 2,
                 n_readers: int = 1):
        if depth < 1 or n_readers < 1:
            raise ValueError("RingChannel needs depth >= 1, n_readers >= 1")
        super().__init__(depth, slot_size, n_readers)
        # The Arena (object_store.py) provides the untracked /dev/shm
        # segment + warm-page machinery; one alloc spans the whole ring.
        self._arena = Arena(self.total_size, name_prefix="rtch")
        self.name = self._arena.name
        self._buf = self._arena.shm.buf
        _HEADER.pack_into(self._buf, 0, MAGIC, 0, self.depth,
                          self.slot_size, self.n_readers, 0)
        for i in range(self.n_readers):
            self._set_cursor(i, 0)
        for s in range(self.depth):
            _SLOT_HEADER.pack_into(self._buf, self._slot_view(s), 0, 0)
        self._writer = None
        self._next_reader = 0

    # The creator can act as the writer directly (input channels).
    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        if self._writer is None:
            self._writer = RingWriter(self.name, self.depth, self.slot_size,
                                      self.n_readers, _attached=self)
        self._writer.write(value, timeout)

    def write_bytes(self, data, timeout: Optional[float] = None) -> None:
        if self._writer is None:
            self._writer = RingWriter(self.name, self.depth, self.slot_size,
                                      self.n_readers, _attached=self)
        self._writer.write_bytes(data, timeout)

    def writer(self) -> "RingWriter":
        return RingWriter(self.name, self.depth, self.slot_size,
                          self.n_readers)

    def reader(self, idx: Optional[int] = None,
               patient: bool = False) -> "RingReader":
        if idx is None:
            idx = self._next_reader
            self._next_reader += 1
        if not 0 <= idx < self.n_readers:
            raise ValueError(f"reader index {idx} out of range "
                             f"(n_readers={self.n_readers})")
        return RingReader(self.name, self.depth, self.slot_size,
                          self.n_readers, idx, patient)

    def destroy(self) -> None:
        self.close()
        self._buf = None
        if self._writer is not None:
            self._writer._buf = None
        self._arena.destroy()

    def __reduce__(self):
        # A pickled channel crosses as a WRITER handle (the single-writer
        # end); consumers must be handed explicit reader(i) objects.
        return (RingWriter, (self.name, self.depth, self.slot_size,
                             self.n_readers))


class RingWriter(_RingBase):
    """The single-writer end; picklable by segment name."""

    def __init__(self, name: str, depth: int, slot_size: int,
                 n_readers: int, _attached=None):
        super().__init__(depth, slot_size, n_readers)
        self.name = name
        if _attached is not None:
            self._seg = None
            self._buf = _attached._buf
        else:
            self._seg = _attach_untracked(name)
            self._buf = self._seg.buf

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        seq = self._writer_seq()
        ser = _serialization_ctx().serialize(value)
        if ser.total_size > self.slot_size:
            # Oversize: park the payload in the object store (zero-copy
            # shm put; remote readers pull via the store transfer path)
            # and ring only the ref. The ref is kept alive writer-side
            # until every reader's cursor passes this seq (see below).
            from ray_tpu._private import worker_api
            ref = worker_api.put(value)
            ser = _serialization_ctx().serialize(_OversizeRef(ref))
            if not hasattr(self, "_held_refs"):
                self._held_refs = {}
            self._held_refs[seq] = ref
        self._write_slot(seq, ser.total_size, ser.write_to, timeout)

    def write_bytes(self, data, timeout: Optional[float] = None) -> None:
        """Write an ALREADY-serialized message (the same wire format
        write() produces). Compiled-DAG loops with recovery armed
        serialize once, cache the private bytes for resend, and ship
        them here — a cached live object could alias a zero-copy view
        onto a ring slot the writer has since recycled."""
        if len(data) > self.slot_size:
            # Oversize falls back through the value path (the payload
            # must ride the object store as a ref).
            self.write(_serialization_ctx().deserialize(data), timeout)
            return

        def _fill(buf):
            buf[:len(data)] = data

        self._write_slot(self._writer_seq(), len(data), _fill, timeout)

    def _write_slot(self, seq: int, size: int, fill, timeout) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        blocked = False
        spin = 0
        while seq - self._min_cursor() >= self.depth:
            if self.closed():
                raise ChannelClosedError(self.name)
            if not blocked:
                blocked = True
                _note_channel_full()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"channel write blocked on full ring for {timeout}s")
            spin += 1
            time.sleep(2e-3 if spin > 2000 else 5e-5)
        if self.closed():
            raise ChannelClosedError(self.name)
        base = self._slot_view(seq)
        _SLOT_HEADER.pack_into(self._buf, base, 2 * seq + 1, 0)
        payload = self._buf[base + _SLOT_HEADER.size:
                            base + _SLOT_HEADER.size + size]
        fill(payload)
        _SLOT_HEADER.pack_into(self._buf, base, 2 * seq + 2, size)
        self._set_writer_seq(seq + 1)
        # Drop refs every reader has consumed (oversize lifetime bound).
        held = getattr(self, "_held_refs", None)
        if held:
            floor = self._min_cursor()
            for s in [s for s in held if s < floor]:
                del held[s]

    def destroy(self) -> None:
        if getattr(self, "_held_refs", None):
            self._held_refs.clear()
        if self._seg is not None:
            try:
                self._seg.close()
            except Exception:  # noqa: BLE001 — zero-copy views may pin it
                pass

    def __reduce__(self):
        return (RingWriter, (self.name, self.depth, self.slot_size,
                             self.n_readers))


class RingReader(_RingBase):
    """One consumer's end: owns reader slot `idx`'s cursor.

    `patient=True` skips the tight-poll rung and waits on the nap
    ladder from the first iteration: the right mode when the producer
    COMPUTES for milliseconds per message (an RL rollout, a learn
    step) — hot-polling through such a wait starves the very process
    the reader is waiting on wherever pipeline participants outnumber
    cores, and no reader-side heuristic can tell the two regimes apart
    (on coarse-timer kernels the nap quantum itself inflates a hot
    tick into the compute-wait range, so adaptive detection latches).
    The CALLER knows its cadence; compiled DAGs plumb it through
    `CompiledDAG.compile(patient_readers=...)`. Default False keeps
    the hot path byte-identical: ~2k tight spins (~100 µs) so an
    actively streaming reader wakes within nanoseconds of the write.
    """

    def __init__(self, name: str, depth: int, slot_size: int,
                 n_readers: int, idx: int, patient: bool = False):
        super().__init__(depth, slot_size, n_readers)
        self.name = name
        self.idx = idx
        self.patient = bool(patient)
        self._seg = _attach_untracked(name)
        self._buf = self._seg.buf
        self._local_cursor = self._cursor(idx)

    _TIGHT_SPINS = 2000      # ~100 µs of polling: covers a hot hop
    _IDLE_SPINS = 20000      # then 2 ms naps: clearly idle

    def read(self, timeout: Optional[float] = None,
             copy: bool = False) -> Any:
        """Next message for THIS reader; blocks until the writer produces
        it. Raises ChannelClosedError once the channel is closed and
        drained (in-flight messages are still delivered first).

        copy=False (default) deserializes zero-copy views onto the ring
        slot — valid until the writer laps it, `depth` messages later.
        copy=True detaches the payload first (one memcpy) so the value
        may be held indefinitely — the right mode for consumers that
        outlive the tick (the compiled DAG's driver-side output reads)."""
        cursor = self._local_cursor
        t_entry = time.monotonic()
        deadline = None if timeout is None else t_entry + timeout
        spin = self._TIGHT_SPINS if self.patient else 0
        next_liveness = t_entry + 2.0
        bad_count = 0
        while True:
            if self._writer_seq() > cursor:
                base = self._slot_view(cursor)
                version, length = _SLOT_HEADER.unpack_from(self._buf, base)
                if version == 2 * cursor + 2 and length <= self.slot_size:
                    payload = self._buf[base + _SLOT_HEADER.size:
                                        base + _SLOT_HEADER.size + length]
                    if copy:
                        payload = memoryview(bytes(payload))
                    try:
                        value = _serialization_ctx().deserialize(payload)
                    except Exception:
                        # Stable header but an unpicklable payload: a torn
                        # store resolves within nanoseconds — retry without
                        # advancing; a payload that KEEPS failing is a
                        # genuinely bad message (hostile/raw writer) and
                        # must raise, not hang a timeout-less read (the
                        # PR 7 discipline).
                        bad_count += 1
                        if bad_count >= 64:
                            raise
                        time.sleep(5e-5)
                        continue
                    v2, l2 = _SLOT_HEADER.unpack_from(self._buf, base)
                    if v2 == version and l2 == length:   # no torn read
                        value = _resolve_payload(value)
                        self._local_cursor = cursor + 1
                        self._set_cursor(self.idx, cursor + 1)
                        return value
                # Torn / lapped header: fall through and spin.
            elif self.closed():
                raise ChannelClosedError(self.name)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel read timed out ({timeout}s)")
            # Backoff ladder: a tight-poll rung (~100 µs — covers a
            # hot pipeline hop, so an ACTIVELY streaming reader wakes
            # within nanoseconds of the write; skipped entirely by
            # PATIENT readers — a known ms-scale producer must get the
            # core, not a polling peer), then 50 µs naps, then 2 ms
            # naps once clearly idle (don't burn a core forever).
            spin += 1
            if spin > self._IDLE_SPINS:
                time.sleep(2e-3)
                if time.monotonic() > next_liveness:
                    next_liveness = time.monotonic() + 2.0
                    if not self._creator_alive():
                        raise ChannelClosedError(
                            f"{self.name}: channel creator is gone")
            elif spin > self._TIGHT_SPINS:
                time.sleep(5e-5)

    def destroy(self) -> None:
        try:
            self._seg.close()
        except Exception:  # noqa: BLE001 — zero-copy views may pin it
            pass

    def __reduce__(self):
        return (RingReader, (self.name, self.depth, self.slot_size,
                             self.n_readers, self.idx, self.patient))


# ---------------------------------------------------------------------------
# Cross-node fallback: the same protocol over the GCS KV + object store.
# ---------------------------------------------------------------------------

_KV_NAMESPACE = "dagch"
_INLINE_LIMIT = 64 << 10


def _kv_put(key: str, value: bytes) -> None:
    from ray_tpu._private import worker_api
    worker_api.internal_kv_put(key.encode(), value, namespace=_KV_NAMESPACE)


def _kv_get(key: str) -> Optional[bytes]:
    from ray_tpu._private import worker_api
    return worker_api.internal_kv_get(key.encode(), namespace=_KV_NAMESPACE)


def _kv_del(key: str) -> None:
    from ray_tpu._private import worker_api
    worker_api.internal_kv_del(key.encode(), namespace=_KV_NAMESPACE)


class StoreChannel:
    """Cross-raylet channel: seq/cursor control rides the GCS KV; payloads
    above the inline limit ride the object store, whose existing
    chunked `store_fetch_remote` transfer moves them node to node.

    Interface-compatible with RingChannel (write / reader(i).read /
    close / destroy) so compiled DAGs pick per EDGE: shm ring when both
    endpoints share a node, this when they don't. Per-message cost is a
    couple of small KV round trips — the fallback trades latency for
    spanning raylets; the zero-RPC tick claim applies to ring edges.
    """

    def __init__(self, channel_id: str, depth: int = 2, n_readers: int = 1,
                 inline_limit: Optional[int] = None, _attach: bool = False):
        self.channel_id = channel_id
        self.depth = int(depth)
        self.n_readers = int(n_readers)
        if inline_limit is None:
            from ray_tpu._private import object_plane
            inline_limit = object_plane.threshold("dag_channel",
                                                  _INLINE_LIMIT)
        self.inline_limit = int(inline_limit)
        # Channel seqs (at/above the resume floor) whose records were
        # written by a PREVIOUS writer incarnation as object refs: the
        # pins died with that writer, so the payloads are presumed gone.
        # resend_bytes() re-seals them in place from cached wire bytes.
        self._stale_ref_seqs: List[int] = []
        # An ATTACHED copy (unpickled on a shipped loop) resumes the
        # persisted writer seq lazily on its first write: a compiled-DAG
        # recovery re-ships the writer role to a surviving/restarted
        # executor, and restarting at 0 would overwrite live message
        # keys that readers' persisted cursors still point past.
        self._seq: Optional[int] = None if _attach else 0
        self._held_refs = {}
        self._next_reader = 0
        self._closed_local = False
        self._gc_upto = 0

    # -- keys ----------------------------------------------------------
    def _mkey(self, seq: int) -> str:
        return f"{self.channel_id}/m/{seq}"

    def _ckey(self, idx: int) -> str:
        return f"{self.channel_id}/c/{idx}"

    def _closed_key(self) -> str:
        return f"{self.channel_id}/closed"

    def _min_cursor(self) -> int:
        lo = None
        for i in range(self.n_readers):
            raw = _kv_get(self._ckey(i))
            cur = int(raw) if raw else 0
            lo = cur if lo is None else min(lo, cur)
        return lo or 0

    def closed(self) -> bool:
        if self._closed_local:
            return True
        return _kv_get(self._closed_key()) is not None

    # -- writer side ---------------------------------------------------
    def _resume_writer_seq(self) -> int:
        """An attached copy derives the persisted writer seq on its
        first write: probe message keys upward from the SLOWEST reader's
        cursor (records are contiguous from there — GC only deletes
        below the min cursor; readers never pass the writer; undelivered
        backlog <= depth keys exist above the GC floor). Restarting at 0
        would overwrite live message keys past the readers' cursors.

        The probe doubles as the dangling-ref census: any undelivered
        record holding an object ref was written by the previous writer
        incarnation, whose pins died with it — those seqs are queued for
        in-place re-sealing by resend_bytes()."""
        seq = self._min_cursor()
        stale = []
        while True:
            body = _kv_get(self._mkey(seq))
            if body is None:
                break
            if body[:1] != b"v":
                stale.append(seq)
            seq += 1
        self._stale_ref_seqs = stale
        return seq

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        ser = _serialization_ctx().serialize(value)
        if ser.total_size > self.inline_limit:
            from ray_tpu._private import worker_api
            ref = worker_api.put(value)
            body = pickle.dumps(("r", ref), protocol=5)
            self._write_body(body, timeout, held_ref=ref)
        else:
            self._write_body(b"v" + ser.to_bytes(), timeout)

    def write_bytes(self, data, timeout: Optional[float] = None) -> None:
        """Write an ALREADY-serialized message (write()'s inline wire
        format); oversize payloads fall back through the value path."""
        if len(data) > self.inline_limit:
            self.write(_serialization_ctx().deserialize(data), timeout)
            return
        self._write_body(b"v" + bytes(data), timeout)

    def _seal_body(self, data, seq: int) -> bytes:
        """Wire bytes -> a sealed KV record owned by THIS writer: inline
        when they fit, else a fresh object-plane put (ref held against
        `seq` so the payload outlives every reader's cursor)."""
        if len(data) <= self.inline_limit:
            return b"v" + bytes(data)
        from ray_tpu._private import worker_api
        ref = worker_api.put(_serialization_ctx().deserialize(data))
        self._held_refs[seq] = ref
        return pickle.dumps(("r", ref), protocol=5)

    def resend_bytes(self, data, timeout: Optional[float] = None) -> None:
        """Recovery resend of a cached already-serialized message.

        Unlike write_bytes, this first RE-SEALS the lowest stale
        oversize record left by the previous writer incarnation: a ref
        written by a dead (or torn-down) writer dangles — its pin died
        with the process — and a reader paused at that record would
        otherwise fail on an object that can never materialize. The
        record is overwritten IN PLACE with a body sealed from the
        cached wire bytes (a fresh put owned by this writer when
        oversize). Readers dedupe replays by the embedded tick seq, so
        re-sealing a slot with a neighboring tick's payload is harmless;
        what matters is that every undelivered record is readable. The
        message is then also appended normally — the blanket-resend
        contract compiled-DAG recovery relies on."""
        if self._seq is None:
            self._seq = self._resume_writer_seq()
        if self._stale_ref_seqs:
            seq = self._stale_ref_seqs.pop(0)
            _kv_put(self._mkey(seq), self._seal_body(data, seq))
        self.write_bytes(data, timeout)

    def _write_body(self, body: bytes, timeout: Optional[float],
                    held_ref=None) -> None:
        if self._seq is None:
            self._seq = self._resume_writer_seq()
        deadline = None if timeout is None else time.monotonic() + timeout
        blocked = False
        while self._seq - self._min_cursor() >= self.depth:
            if self.closed():
                raise ChannelClosedError(self.channel_id)
            if not blocked:
                blocked = True
                _note_channel_full()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"channel write blocked on full window for {timeout}s")
            time.sleep(0.02)
        if self.closed():
            raise ChannelClosedError(self.channel_id)
        if held_ref is not None:
            self._held_refs[self._seq] = held_ref
        _kv_put(self._mkey(self._seq), body)
        self._seq += 1
        floor = self._min_cursor()
        for s in [s for s in self._held_refs if s < floor]:
            del self._held_refs[s]
        # Control records every reader consumed are GC'd exactly once.
        for s in range(self._gc_upto, floor):
            _kv_del(self._mkey(s))
        self._gc_upto = max(self._gc_upto, floor)

    def reader(self, idx: Optional[int] = None,
               patient: bool = False) -> "StoreReader":
        # `patient` accepted for interface parity with RingChannel
        # (KV-backed reads already wait on a nap ladder).
        if idx is None:
            idx = self._next_reader
            self._next_reader += 1
        if not 0 <= idx < self.n_readers:
            raise ValueError(f"reader index {idx} out of range")
        return StoreReader(self.channel_id, self.depth, self.n_readers,
                           idx)

    # -- lifecycle -----------------------------------------------------
    def reopen(self) -> None:
        """Recovery counterpart of close(): drop the closed record so the
        channel carries traffic again. Message bodies and per-reader
        cursors live in the KV and are untouched — a reader (even one
        whose hosting process was restarted) resumes from its persisted
        cursor. Call only after every attached loop exited."""
        self._closed_local = False
        try:
            _kv_del(self._closed_key())
        except Exception:  # noqa: BLE001 — cluster already down
            pass

    def close(self) -> None:
        self._closed_local = True
        try:
            import asyncio
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                loop = None
            if loop is not None:
                # On-loop caller (the DAG failure watcher runs on the
                # core loop): the sync KV wrapper would deadlock here.
                from ray_tpu._private import worker_api
                core = worker_api.peek_core()
                if core is not None:
                    asyncio.ensure_future(worker_api.internal_kv_put_async(
                        core, self._closed_key().encode(), b"1",
                        namespace=_KV_NAMESPACE))
                return
            _kv_put(self._closed_key(), b"1")
        except Exception:  # noqa: BLE001 — closing a dead cluster
            pass

    def destroy(self) -> None:
        self._held_refs.clear()
        try:
            from ray_tpu._private import worker_api
            for k in worker_api.internal_kv_keys(
                    f"{self.channel_id}/".encode(), namespace=_KV_NAMESPACE):
                worker_api.internal_kv_del(k, namespace=_KV_NAMESPACE)
        except Exception:  # noqa: BLE001 — cluster already down
            pass

    def __reduce__(self):
        # Crossing processes hands over the WRITER role (single-writer:
        # the previous writer stops before the copy starts — compile
        # ships before the first write, recovery awaits the old loop's
        # exit). The attached copy resolves the persisted writer seq
        # lazily on its FIRST WRITE, never here: unpickling happens on
        # the receiving core loop, where a blocking KV round trip would
        # deadlock.
        return (StoreChannel,
                (self.channel_id, self.depth, self.n_readers,
                 self.inline_limit, True))


class StoreReader:
    """One consumer's end of a StoreChannel. The persisted cursor is
    resolved lazily on the first read (never at unpickle time — that
    runs on the receiver's event loop)."""

    def __init__(self, channel_id: str, depth: int, n_readers: int,
                 idx: int):
        self.channel_id = channel_id
        self.depth = depth
        self.n_readers = n_readers
        self.idx = idx
        self._cursor: Optional[int] = None

    def read(self, timeout: Optional[float] = None,
             copy: bool = False) -> Any:
        # `copy` accepted for interface parity with RingReader; KV/store
        # payloads are already private bytes, never shared-slot views.
        if self._cursor is None:
            raw = _kv_get(f"{self.channel_id}/c/{self.idx}")
            self._cursor = int(raw) if raw else 0
        deadline = None if timeout is None else time.monotonic() + timeout
        key = f"{self.channel_id}/m/{self._cursor}"
        napped = 0
        while True:
            body = _kv_get(key)
            if body is not None:
                break
            if _kv_get(f"{self.channel_id}/closed") is not None:
                raise ChannelClosedError(self.channel_id)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel read timed out ({timeout}s)")
            napped += 1
            time.sleep(min(0.05, 0.005 * napped))
        if body[:1] == b"v":
            value = _serialization_ctx().deserialize(body[1:])
        else:
            value = self._resolve_ref(body, key, deadline)
        self._cursor += 1
        _kv_put(f"{self.channel_id}/c/{self.idx}", str(self._cursor).encode())
        return value

    _REF_GET_SLICE_S = 5.0     # per-attempt bound on the cross-node get
    _REF_LOST_RETRIES = 3      # lost-object re-reads before failing typed

    def _resolve_ref(self, body: bytes, key: str, deadline):
        """Materialize an oversize record. Same-node payloads pin
        straight out of the local object plane (zero-copy view, no owner
        round trip — the control word was the only KV hop); cross-node
        ones ride the store transfer path. A ref whose owner died is
        retried against the CONTROL WORD, not the object: recovery
        re-seals the record in place from the writer's cached wire
        bytes, so the reader re-reads the key between bounded get
        attempts and fails typed (ChannelDataLostError) only if no
        re-seal ever lands — never a silent multi-minute hang."""
        from ray_tpu import exceptions as rexc
        from ray_tpu._private import worker_api
        lost = 0
        last_err = None
        while True:
            kind, ref = pickle.loads(body)
            try:
                hit = worker_api.get_local(ref)
            except Exception:  # noqa: BLE001 — fall through to full get
                hit = None
            if hit is not None:
                return hit[0]
            try:
                return worker_api.get(ref, timeout=self._REF_GET_SLICE_S)
            except rexc.ObjectLostError as e:   # owner died / copies gone
                lost += 1
                last_err = e
            except (rexc.GetTimeoutError, TimeoutError):
                # Slow fetch, not a dead owner: honor the read deadline.
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"channel read timed out resolving {key}")
                continue
            time.sleep(0.2)
            resealed = _kv_get(key)
            if resealed is not None and resealed != body:
                if resealed[:1] == b"v":
                    return _serialization_ctx().deserialize(resealed[1:])
                body = resealed
                continue
            if lost >= self._REF_LOST_RETRIES or (
                    deadline is not None and time.monotonic() > deadline):
                raise ChannelDataLostError(
                    f"{key}: oversize payload lost — its writer died "
                    f"before recovery re-sealed the record") from last_err

    def close(self) -> None:
        pass

    def destroy(self) -> None:
        pass

    def __reduce__(self):
        return (StoreReader, (self.channel_id, self.depth, self.n_readers,
                              self.idx))
