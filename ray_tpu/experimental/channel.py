"""Mutable shared-memory channels: the low-latency substrate for compiled
DAGs (reference: python/ray/experimental/channel.py:49 over
src/ray/core_worker/experimental_mutable_object_manager.h).

A Channel is a fixed-capacity shared-memory segment that is REUSED for
every message — no per-message allocation, sealing, or RPC. Writes bump a
seqlock version header; readers spin (with microsleeps) until a new
consistent version appears. Same-node process pairs see single-digit-µs
hand-off, which is what Serve replica chains and MPMD pipeline stages need
— the task/actor RPC path costs ~1ms per hop.

Layout: [version u64][length u64][payload ...]. The version is odd while a
write is in flight (seqlock), even when stable; readers re-check the
version after copying to guard torn reads.
"""

from __future__ import annotations

import pickle
import struct
import time
from multiprocessing import shared_memory
from typing import Any, Optional

_HEADER = struct.Struct("<QQ")
HEADER_SIZE = _HEADER.size
_CLOSED_TAG = b"__RAY_TPU_CHANNEL_CLOSED__"


class ChannelClosedError(Exception):
    pass


class Channel:
    def __init__(self, max_size: int = 1 << 20, *, _name: Optional[str] = None):
        self.max_size = max_size
        if _name is None:
            self._shm = shared_memory.SharedMemory(
                create=True, size=HEADER_SIZE + max_size)
            self._buf = self._shm.buf
            _HEADER.pack_into(self._buf, 0, 0, 0)
            self._creator = True
        else:
            # Untracked attach: SharedMemory(name=...) would spawn a
            # resource-tracker process per attaching worker, and (observed
            # on this box) those trackers spin a full core after fork.
            from ray_tpu._private.object_store import _attach_untracked
            self._shm = _attach_untracked(_name)
            self._buf = self._shm.buf
            self._creator = False
        self._last_read_version = 0

    @property
    def name(self) -> str:
        return self._shm.name

    # -- writer side --------------------------------------------------

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        data = pickle.dumps(value, protocol=5)
        self._write_bytes(data)

    def _write_bytes(self, data: bytes) -> None:
        if len(data) > self.max_size:
            raise ValueError(
                f"message of {len(data)} bytes exceeds channel capacity "
                f"{self.max_size}; size channels for the largest message")
        version, _ = _HEADER.unpack_from(self._buf, 0)
        # Odd = write in flight (seqlock).
        _HEADER.pack_into(self._buf, 0, version + 1, len(data))
        self._buf[HEADER_SIZE:HEADER_SIZE + len(data)] = data
        _HEADER.pack_into(self._buf, 0, version + 2, len(data))

    def close(self) -> None:
        """Wake readers with ChannelClosedError on their next read."""
        try:
            self._write_bytes(_CLOSED_TAG)
        except Exception:
            pass

    # -- reader side --------------------------------------------------

    def read(self, timeout: Optional[float] = None) -> Any:
        """Block until a version newer than the last read; return value.

        Torn-read guards, in order of subtlety: the header re-read must
        match on BOTH fields (the 16-byte header is two non-atomic
        loads — a reader can observe the NEW version with the STALE
        length, because memcpy may load the fields in either order), and
        a payload that still fails to unpickle is treated as torn and
        RETRIED rather than raised — the writer finishes its store
        nanoseconds later, and surfacing a transient tear as EOFError
        killed executor loops (observed as compiled-DAG wedges). The
        read cursor only ever advances past a fully-validated message,
        so a retry can never skip one."""
        deadline = None if timeout is None else time.monotonic() + timeout
        spin = 0
        bad_version = bad_count = 0
        while True:
            version, length = _HEADER.unpack_from(self._buf, 0)
            if (version % 2 == 0 and version > self._last_read_version
                    and length <= self.max_size):
                payload = bytes(
                    self._buf[HEADER_SIZE:HEADER_SIZE + length])
                v2, l2 = _HEADER.unpack_from(self._buf, 0)
                if v2 == version and l2 == length:   # no torn read
                    if payload == _CLOSED_TAG:
                        self._last_read_version = version
                        raise ChannelClosedError(self._shm.name)
                    try:
                        value = pickle.loads(payload)
                    except Exception:
                        # Torn payload despite a stable header: spin —
                        # the next copy sees the completed write within
                        # nanoseconds. But a payload that KEEPS failing
                        # at the same version isn't torn (unpicklable
                        # value — class missing in this process): raise
                        # it rather than hang a timeout-less reader.
                        if version != bad_version:
                            bad_version, bad_count = version, 1
                        else:
                            bad_count += 1
                        if bad_count >= 64:
                            raise
                        time.sleep(5e-5)
                    else:
                        self._last_read_version = version
                        return value
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel read timed out ({timeout}s)")
            # Micro-backoff: tight spin first (latency), 50 µs naps next,
            # 2 ms naps once clearly idle (don't burn a core forever).
            spin += 1
            if spin > 20000:
                time.sleep(2e-3)
            elif spin > 200:
                time.sleep(5e-5)

    # -- lifecycle ----------------------------------------------------

    def destroy(self) -> None:
        try:
            self._shm.close()
        except Exception:
            pass
        if self._creator:
            try:
                self._shm.unlink()
            except Exception:
                pass

    def __reduce__(self):
        # Ships by segment name: the receiving process attaches to the
        # same memory.
        return (_attach_channel, (self._shm.name, self.max_size))


def _attach_channel(name: str, max_size: int) -> "Channel":
    return Channel(max_size, _name=name)
