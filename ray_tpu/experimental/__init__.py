"""Experimental APIs (reference: python/ray/experimental/)."""

from ray_tpu.experimental.channel import Channel, ChannelClosedError
from ray_tpu.experimental.channels import (RingChannel, RingReader,
                                           RingWriter, StoreChannel,
                                           StoreReader)

__all__ = ["Channel", "ChannelClosedError", "RingChannel", "RingReader",
           "RingWriter", "StoreChannel", "StoreReader"]
