from ray_tpu.parallel.mesh import (MeshConfig, build_mesh, get_slice_info,
                                   fake_mesh)
from ray_tpu.parallel.sharding import (ShardingRules, ShardingStrategy,
                                       shard_params, batch_sharding,
                                       strategy_from_name)

__all__ = [
    "MeshConfig", "build_mesh", "get_slice_info", "fake_mesh",
    "ShardingRules", "ShardingStrategy", "shard_params", "batch_sharding",
    "strategy_from_name",
]
