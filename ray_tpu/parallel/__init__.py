from ray_tpu.parallel.mesh import (MeshConfig, build_mesh, get_slice_info,
                                   fake_mesh)
from ray_tpu.parallel.sharding import (ShardingRules, ShardingStrategy,
                                       shard_params, batch_sharding,
                                       strategy_from_name)

__all__ = [
    "MeshConfig", "build_mesh", "get_slice_info", "fake_mesh",
    "ShardingRules", "ShardingStrategy", "shard_params", "batch_sharding",
    "strategy_from_name", "StagePipeline",
]


def __getattr__(name):
    # Lazy: StagePipeline pulls in the model stack via pipeline.py; the
    # common mesh/sharding import path must not pay for it.
    if name == "StagePipeline":
        from ray_tpu.parallel.pipeline import StagePipeline
        return StagePipeline
    raise AttributeError(name)
