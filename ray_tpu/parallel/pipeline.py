"""Pipeline parallelism: GPipe schedule over the 'pipeline' mesh axis.

TPU-first design (the reference's closest substrate is compiled DAGs over
mutable-plasma channels, python/ray/dag/compiled_dag_node.py:141 +
python/ray/experimental/channel.py:49 — actor stages linked by channels;
here the whole pipeline is ONE XLA program): transformer layers are stacked
on a leading axis sharded over 'pipeline', and a `shard_map` runs the GPipe
microbatch schedule as a `lax.scan` over ticks with `lax.ppermute` moving
activations stage->stage over ICI. Gradients flow through the schedule
(ppermute transposes to the reverse permute), so pipeline-parallel training
is just `jax.grad` of this loss.

Composes with data parallel (batch sharded over 'data') and tensor parallel
(Megatron column/row sharding inside each stage with manual psum over
'tensor' — inside shard_map collectives are explicit).

Memory: stage activations are carried through the scan (GPipe-style full
activation footprint / num_microbatches granularity); per-layer remat
(cfg.remat) bounds the within-stage footprint.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # 0.4.x keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map


def _shard_map_compat(f, mesh, in_specs, out_specs):
    """shard_map across jax versions: the replication-check kwarg was
    renamed check_rep -> check_vma; disable whichever this jax has (the
    body mixes collectives manually — 0.4.x's rep inference rejects the
    per-rank lax.cond branches)."""
    import inspect
    params = inspect.signature(_shard_map).parameters
    kw = {}
    if "check_vma" in params:
        kw["check_vma"] = False
    elif "check_rep" in params:
        kw["check_rep"] = False
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)

from ray_tpu.models.gpt import GPTConfig, _rmsnorm, _rope
from ray_tpu.ops.attention import flash_attention, mha_reference


def gpt_params_to_pp(params: Dict) -> Dict:
    """Convert the GPT param pytree (list of per-layer dicts) to the
    pipeline layout: identical leaves stacked on a leading layer axis."""
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                     *params["layers"])
    out = {k: v for k, v in params.items() if k != "layers"}
    out["stacked"] = stacked
    return out


def pp_params_to_gpt(pp_params: Dict, n_layers: int) -> Dict:
    """Inverse of gpt_params_to_pp (checkpoint interchange)."""
    out = {k: v for k, v in pp_params.items() if k != "stacked"}
    out["layers"] = [
        jax.tree_util.tree_map(lambda x, i=i: x[i], pp_params["stacked"])
        for i in range(n_layers)
    ]
    return out


def _pp_attention(layer, x, cfg: GPTConfig, positions, tp: int):
    """Attention with heads split over 'tensor' (column-parallel qkv,
    row-parallel out projection; psum completes the row-parallel matmul)."""
    b, s, d = x.shape
    dt = cfg.dtype
    h_local = cfg.n_heads // tp
    hd = cfg.head_dim

    def proj(w):  # w local: [d, d/tp]
        return jnp.einsum("bsd,de->bse", x, w.astype(dt))

    q = proj(layer["attn"]["wq"]).reshape(b, s, h_local, hd)
    k = proj(layer["attn"]["wk"]).reshape(b, s, h_local, hd)
    v = proj(layer["attn"]["wv"]).reshape(b, s, h_local, hd)
    q = _rope(q.transpose(0, 2, 1, 3), cfg.rope_theta, positions)
    k = _rope(k.transpose(0, 2, 1, 3), cfg.rope_theta, positions)
    v = v.transpose(0, 2, 1, 3)
    if cfg.attention == "reference":
        o = mha_reference(q, k, v, causal=True)
    else:
        o = flash_attention(q, k, v, causal=True)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, d // tp)
    y = jnp.einsum("bse,ed->bsd", o, layer["attn"]["wo"].astype(dt))
    if tp > 1:
        y = lax.psum(y, "tensor")
    return y


def _pp_mlp(layer, x, cfg: GPTConfig, tp: int):
    dt = cfg.dtype
    m = layer["mlp"]
    gate = jnp.einsum("bsd,df->bsf", x, m["w_gate"].astype(dt))
    up = jnp.einsum("bsd,df->bsf", x, m["w_up"].astype(dt))
    y = jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                   m["w_down"].astype(dt))
    if tp > 1:
        y = lax.psum(y, "tensor")
    return y


def make_gpt_pp_loss(cfg: GPTConfig, mesh: Mesh, num_microbatches: int):
    """Build loss_fn(pp_params, batch) running the GPipe schedule.

    batch: {"tokens": [B, S+1]}; B is the GLOBAL batch, sharded over 'data'.
    The per-data-shard batch must divide num_microbatches.
    """
    n_stages = mesh.shape["pipeline"]
    tp = mesh.shape.get("tensor", 1)
    if cfg.n_layers % n_stages != 0:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pipeline={n_stages}")
    if cfg.n_experts > 0:
        raise ValueError("pipeline preset supports dense MLP layers (use "
                         "'ep' compositions for MoE)")
    if cfg.n_heads % tp != 0:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={tp}")
    M = num_microbatches
    eps = cfg.rmsnorm_eps
    dt = cfg.dtype

    def body(stacked, embed_tbl, final_scale, lm_head, inputs, targets):
        # Per-device blocks: stacked [L/S, ...] (+tensor-sharded matrices),
        # inputs/targets [B/data, S].
        rank = lax.axis_index("pipeline")
        b, s = inputs.shape
        mb = b // M
        if b % M != 0:
            raise ValueError(f"per-shard batch {b} not divisible by "
                             f"microbatches {M}")
        inputs_mb = inputs.reshape(M, mb, s)
        targets_mb = targets.reshape(M, mb, s)
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (mb, s))

        def stage_fn(x):
            def layer_body(x, layer):
                h = x + _pp_attention(
                    layer, _rmsnorm(x, layer["ln1"]["scale"], eps), cfg,
                    positions, tp)
                normed = _rmsnorm(h, layer["ln2"]["scale"], eps)
                return h + _pp_mlp(layer, normed, cfg, tp), None

            if cfg.remat:
                layer_body = jax.checkpoint(layer_body)
            x, _ = lax.scan(layer_body, x, stacked)
            return x

        def head_loss(y, tgt):
            xf = _rmsnorm(y, final_scale, eps)
            if cfg.tie_embeddings:
                logits = jnp.einsum("bsd,vd->bsv", xf, embed_tbl.astype(dt))
            else:
                logits = jnp.einsum("bsd,dv->bsv", xf, lm_head.astype(dt))
            logits = logits.astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(
                logp, tgt[..., None], axis=-1)[..., 0]
            mask = (tgt >= 0).astype(jnp.float32)
            return jnp.sum(nll * mask), jnp.sum(mask)

        n_ticks = M + n_stages - 1

        def tick(carry, t):
            recv, loss_sum, loss_cnt = carry
            inject_idx = jnp.clip(t, 0, M - 1)
            # Only rank 0 pays for the embedding lookup (real branch on TPU).
            injected = lax.cond(
                rank == 0,
                lambda: embed_tbl.astype(dt)[
                    lax.dynamic_index_in_dim(inputs_mb, inject_idx, 0,
                                             keepdims=False)],
                lambda: jnp.zeros((mb, s, embed_tbl.shape[1]), dt))
            x_in = jnp.where(rank == 0, injected, recv)
            y = stage_fn(x_in)
            out_idx = t - (n_stages - 1)
            valid = (out_idx >= 0) & (rank == n_stages - 1)
            tgt = lax.dynamic_index_in_dim(
                targets_mb, jnp.clip(out_idx, 0, M - 1), 0, keepdims=False)
            ls, lc = lax.cond(
                valid,
                lambda: head_loss(y, tgt),
                lambda: (jnp.float32(0), jnp.float32(0)))
            send = lax.ppermute(
                y, "pipeline",
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (send, loss_sum + ls, loss_cnt + lc), None

        zeros = jnp.zeros((mb, s, embed_tbl.shape[1]), dt)
        (_, lsum, lcnt), _ = lax.scan(
            tick, (zeros, jnp.float32(0), jnp.float32(0)),
            jnp.arange(n_ticks))
        # Loss lives on the last pipeline rank of each data shard; reduce to
        # the global mean, replicated everywhere (out_spec P()).
        lsum = lax.psum(lsum, ("data", "pipeline"))
        lcnt = lax.psum(lcnt, ("data", "pipeline"))
        return lsum / jnp.maximum(lcnt, 1.0)

    # Specs for the pp param layout; tensor-parallel matrices carry their
    # Megatron axes (must match the 'pp'/'pp_tp' ShardingRules).
    def _stacked_spec(path_leaf):
        path, leaf = path_leaf
        if tp > 1:
            name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                            for p in path)
            if "wo" in name or "w_down" in name:
                return P("pipeline", "tensor", None)
            if any(k in name for k in ("wq", "wk", "wv", "w_gate", "w_up")):
                return P("pipeline", None, "tensor")
        return P("pipeline", *([None] * (leaf.ndim - 1)))

    def loss_fn(pp_params, batch):
        stacked = pp_params["stacked"]
        stacked_specs = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(stacked),
            [_stacked_spec(pl) for pl in
             jax.tree_util.tree_flatten_with_path(stacked)[0]])
        lm_head = pp_params.get("lm_head", pp_params["embed"]["table"])
        tokens = batch["tokens"]
        fn = _shard_map_compat(
            body, mesh=mesh,
            in_specs=(stacked_specs, P(), P(), P(), P("data"), P("data")),
            out_specs=P())
        return fn(stacked, pp_params["embed"]["table"],
                  pp_params["final_norm"]["scale"], lm_head,
                  tokens[:, :-1], tokens[:, 1:])

    return loss_fn


# ---------------------------------------------------------------------------
# MPMD stage pipelines over the compiled-DAG substrate.
#
# The GPipe loss above is SPMD: one XLA program, ppermute over ICI. The
# MPMD shape (PAPERS.md, arXiv:2412.14374) runs each stage as its OWN
# program on its own slice/process, with activations crossing stages
# through channels — which is exactly the compiled-DAG substrate: a
# stage tick costs one shm channel write, not a task RPC round trip.
# ---------------------------------------------------------------------------


class StagePipeline:
    """A linear chain of actor stages compiled onto reusable channels.

    ``stages`` are live actor handles; each tick flows the input through
    ``stage[0].method -> stage[1].method -> ...`` over pre-leased
    workers and shm ring channels (one channel write per hop).
    ``channel_depth`` microbatches can be in flight at once — the GPipe
    bubble shrinks to (n_stages - 1) ticks, and backpressure from the
    slowest stage bounds memory instead of an unbounded queue.

    Usage::

        pipe = StagePipeline([s0, s1, s2], method="apply", channel_depth=4)
        outs = pipe.run(microbatches)      # pipelined map, order-preserving
        pipe.teardown()                    # or `with StagePipeline(...)`
    """

    def __init__(self, stages, method: str = "__call__", *,
                 channel_depth: int = 4, max_message_size: int = 1 << 20,
                 tick_replay: bool = True):
        """tick_replay=True (default) arms the compiled DAG's in-place
        recovery: a stage actor dying mid-stream is restarted (give the
        stages `max_restarts`!), its lease re-pinned, channels re-homed
        and every unacknowledged microbatch replayed exactly once —
        run() simply keeps returning results. tick_replay=False keeps
        the typed fail-fast `DagExecutionError`."""
        if not stages:
            raise ValueError("StagePipeline needs at least one stage")
        from ray_tpu.dag.compiled import CompiledDAG
        from ray_tpu.dag.dag_node import InputNode
        with InputNode() as inp:
            node = inp
            for handle in stages:
                node = getattr(handle, method).bind(node)
        self.n_stages = len(stages)
        self.channel_depth = channel_depth
        self._dag = CompiledDAG.compile(
            node, channel_depth=channel_depth,
            max_message_size=max_message_size,
            tick_replay=tick_replay)

    def submit(self, value):
        """Inject one microbatch; returns a DagRef. The input write
        blocks once `channel_depth` ticks are in flight (backpressure) —
        a single-threaded caller must collect at least every
        `channel_depth` submissions or it deadlocks itself (run() does
        the windowing for you)."""
        return self._dag.execute_async(value)

    def run(self, inputs, timeout: float = None):
        """Pipelined map over `inputs`, outputs in input order.

        Windowed submit/collect: at most `channel_depth` ticks stay
        uncollected — that already keeps every stage busy (the rings
        hold `depth` messages per edge), and submitting further ahead
        from THIS thread would block the input write with nobody
        draining outputs."""
        from collections import deque
        pending = deque()
        out = []
        for x in inputs:
            if len(pending) >= self.channel_depth:
                out.append(pending.popleft().result(timeout))
            pending.append(self.submit(x))
        while pending:
            out.append(pending.popleft().result(timeout))
        return out

    def stats(self) -> dict:
        return self._dag.stats()

    def teardown(self):
        self._dag.teardown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.teardown()
        return False
