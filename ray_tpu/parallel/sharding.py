"""Sharding strategies: DP / FSDP(ZeRO) / TP / PP / SP / EP as pjit specs.

The TPU-native replacement for the reference's wrapped-framework parallelism
(python/ray/train/torch/train_loop_utils.py:158 prepare_model DDP/FSDP wrap,
SURVEY.md §2.5): every strategy is a set of PartitionSpec rules applied to the
parameter pytree + a batch sharding, compiled by XLA/GSPMD — no runtime
process-group object.

Rules match on the parameter's path (joined with '/'); first match wins.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


@dataclass
class ShardingRules:
    """Ordered (regex, PartitionSpec) rules + a default."""

    rules: List[Tuple[str, P]] = field(default_factory=list)
    default: P = P()

    def spec_for(self, path: str, shape: Tuple[int, ...]):
        for pattern, spec in self.rules:
            if re.search(pattern, path):
                if spec is FSDP_LARGEST:
                    return spec
                if spec is PP_STACKED:
                    return P("pipeline", *([None] * (max(len(shape), 1) - 1)))
                return _truncate_spec(spec, shape)
        if self.default is FSDP_LARGEST:
            return self.default
        return _truncate_spec(self.default, shape)


def _truncate_spec(spec: P, shape: Tuple[int, ...]) -> P:
    """Trim/pad a spec to the array rank so one rule covers kernel+bias."""
    parts = tuple(spec)
    if len(parts) > len(shape):
        parts = parts[-len(shape):] if len(shape) > 0 else ()
    elif len(parts) < len(shape):
        parts = (None,) * (len(shape) - len(parts)) + parts
    return P(*parts)


class ShardingStrategy:
    """A named parallelism strategy = param rules + batch spec + remat policy.

    TPU-first equivalents of the reference inventory (SURVEY.md §2.5):
      dp    -> pure data parallel (params replicated)
      fsdp  -> ZeRO-3: params/opt-state sharded over ('fsdp',) largest dim
      tp    -> Megatron-style tensor parallel over 'tensor'
      tp_fsdp / dp_tp / 3d -> compositions
      sp    -> sequence parallel: batch sharded over tokens ('sequence')
      ep    -> expert parallel (MoE layers over 'expert')
    """

    def __init__(self, name: str, param_rules: ShardingRules,
                 batch_spec: P, data_axes: Sequence[str] = ("data",)):
        self.name = name
        self.param_rules = param_rules
        self.batch_spec = batch_spec
        self.data_axes = tuple(data_axes)

    # ---- presets ----

    @staticmethod
    def dp() -> "ShardingStrategy":
        return ShardingStrategy("dp", ShardingRules(), P("data"))

    @staticmethod
    def fsdp() -> "ShardingStrategy":
        """ZeRO-3: every weight matrix sharded on its largest dim over
        ('fsdp',); XLA all-gathers params per layer and reduce-scatters
        grads (what DeepSpeed/FSDP do imperatively, done by GSPMD)."""
        rules = ShardingRules(rules=[(r".*", FSDP_LARGEST)], default=P())
        return ShardingStrategy("fsdp", rules, P(("data", "fsdp")))

    @staticmethod
    def tp_transformer() -> "ShardingStrategy":
        """Megatron TP for the transformer layout in ray_tpu.models.gpt:
        column-parallel qkv/up projections, row-parallel out/down."""
        t = "tensor"
        rules = ShardingRules(rules=[
            (r"attn/(wq|wk|wv)", P(None, t)),
            (r"attn/wo", P(t, None)),
            (r"mlp/(w_up|w_gate)", P(None, t)),
            (r"mlp/w_down", P(t, None)),
            (r"embed/table", P(t, None)),
            (r"lm_head", P(None, t)),
            (r"moe/.*w_up", P("expert", None, t)),
            (r"moe/.*w_down", P("expert", t, None)),
            (r"moe/router", P(None, None)),
        ], default=P())
        return ShardingStrategy("tp", rules, P("data"))

    @staticmethod
    def tp_fsdp() -> "ShardingStrategy":
        """2D: TP inner + FSDP outer on the complementary dim."""
        t = "tensor"
        f = "fsdp"
        rules = ShardingRules(rules=[
            (r"attn/(wq|wk|wv)", P(f, t)),
            (r"attn/wo", P(t, f)),
            (r"mlp/(w_up|w_gate)", P(f, t)),
            (r"mlp/w_down", P(t, f)),
            # Vocab over both axes, d_model replicated: a d-sharded gather
            # output cannot transition to batch-sharded activations without
            # an involuntary full rematerialization (permuted tile order),
            # while a vocab-sharded gather resolves via masked lookup +
            # all-reduce and reshards to the batch spec cheaply.
            (r"embed/table", P((t, f), None)),
            (r"lm_head", P(f, t)),
            (r"moe/.*w_up", P("expert", f, t)),
            (r"moe/.*w_down", P("expert", t, f)),
            (r"moe/router", P(None, None)),
        ], default=FSDP_LARGEST)
        return ShardingStrategy("tp_fsdp", rules, P(("data", "fsdp")))

    @staticmethod
    def pp() -> "ShardingStrategy":
        """Pipeline parallel: stacked layer params sharded on the leading
        (layer) axis over 'pipeline' (see ray_tpu.parallel.pipeline for the
        GPipe schedule those shardings feed)."""
        rules = ShardingRules(rules=[(r"stacked/", PP_STACKED)], default=P())
        return ShardingStrategy("pp", rules, P("data"))

    @staticmethod
    def pp_tp() -> "ShardingStrategy":
        """Pipeline outer + Megatron tensor parallel inside each stage."""
        t = "tensor"
        pl = "pipeline"
        rules = ShardingRules(rules=[
            (r"stacked/attn/(wq|wk|wv)", P(pl, None, t)),
            (r"stacked/attn/wo", P(pl, t, None)),
            (r"stacked/mlp/(w_gate|w_up)", P(pl, None, t)),
            (r"stacked/mlp/w_down", P(pl, t, None)),
            (r"stacked/", PP_STACKED),
        ], default=P())
        return ShardingStrategy("pp_tp", rules, P("data"))

    @staticmethod
    def sp() -> "ShardingStrategy":
        """Sequence/context parallel: tokens sharded over 'sequence';
        used with ring attention (ray_tpu.ops.ring_attention)."""
        return ShardingStrategy(
            "sp", ShardingRules(), P(("data",), "sequence"),
        )

    @property
    def activation_spec(self) -> P:
        """Canonical sharding for [batch, seq, d_model] activations.

        Constraining the residual stream to this spec at layer boundaries
        stops GSPMD from propagating conflicting weight shardings onto
        activation gradients (which shows up as "involuntary full
        rematerialization" warnings and replicated resharding on the
        backward add_any accumulations).
        """
        parts = tuple(self.batch_spec)
        assert len(parts) <= 3, f"batch_spec {self.batch_spec} has rank > 3"
        return P(*(parts + (None,) * (3 - len(parts))))

    def activation_sharding(self, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.activation_spec)

    def batch_sharding(self, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.batch_spec)

    def param_shardings(self, mesh: Mesh, params: Any):
        """Pytree of NamedShardings matching `params`' structure."""
        def spec(path, leaf):
            shape = np.shape(leaf)
            ps = self.param_rules.spec_for(_path_str(path), shape)
            ps = _subdivide_largest(ps, shape, mesh)
            return NamedSharding(mesh, ps)
        return jax.tree_util.tree_map_with_path(spec, params)

    def shard_params(self, mesh: Mesh, params: Any):
        shardings = self.param_shardings(mesh, params)
        return jax.device_put(params, shardings)


class _FsdpLargestMarker:
    """Sentinel: shard the largest divisible dim over 'fsdp'."""

    def __repr__(self):
        return "FSDP_LARGEST"


FSDP_LARGEST = _FsdpLargestMarker()


class _PpStackedMarker:
    """Sentinel: shard the leading (stacked-layer) dim over 'pipeline'."""

    def __repr__(self):
        return "PP_STACKED"


PP_STACKED = _PpStackedMarker()


def _subdivide_largest(spec, shape: Tuple[int, ...], mesh: Mesh) -> P:
    if spec is not FSDP_LARGEST:
        return spec
    fsdp_size = mesh.shape.get("fsdp", 1)
    if fsdp_size <= 1 or not shape:
        return P()
    # Pick the largest dim divisible by the fsdp axis.
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size:
            parts: List = [None] * len(shape)
            parts[i] = "fsdp"
            return P(*parts)
    return P()


def strategy_from_name(name: str) -> ShardingStrategy:
    presets = {
        "dp": ShardingStrategy.dp,
        "fsdp": ShardingStrategy.fsdp,
        "tp": ShardingStrategy.tp_transformer,
        "tp_fsdp": ShardingStrategy.tp_fsdp,
        "sp": ShardingStrategy.sp,
        "pp": ShardingStrategy.pp,
        "pp_tp": ShardingStrategy.pp_tp,
    }
    if name not in presets:
        raise ValueError(f"unknown strategy '{name}'; one of {list(presets)}")
    return presets[name]()


def shard_params(params, mesh: Mesh, strategy: "ShardingStrategy | str"):
    if isinstance(strategy, str):
        strategy = strategy_from_name(strategy)
    return strategy.shard_params(mesh, params)


def batch_sharding(mesh: Mesh, strategy: "ShardingStrategy | str"):
    if isinstance(strategy, str):
        strategy = strategy_from_name(strategy)
    return strategy.batch_sharding(mesh)
