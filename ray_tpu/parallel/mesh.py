"""Device-mesh construction and TPU slice topology.

The TPU-native replacement for the reference's collective *group* runtime
(python/ray/util/collective/collective.py): instead of constructing an NCCL
communicator object at runtime, parallelism is expressed by (a) building a
`jax.sharding.Mesh` whose axes map onto the ICI torus, and (b) compiling
programs whose collectives (psum/ppermute/all_to_all) ride those axes. Mesh
axes, in canonical order:

    ("data", "fsdp", "expert", "pipeline", "sequence", "tensor")

"tensor" is innermost so tensor-parallel collectives use the
fastest/nearest ICI links; "data" is outermost so pure-DP gradient
reductions tolerate DCN hops in multi-slice deployments (scaling-book
mesh-ordering recipe).

Slice topology detection mirrors the reference's TPU accelerator manager
(python/ray/_private/accelerators/tpu.py:75 TPUAcceleratorManager): TPU env
vars / GCE metadata name the slice and its chip count; a v4-16 slice shows up
as a gang-schedulable unit with one `TPU-<gen>-head` bundle.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

AXIS_ORDER = ("data", "fsdp", "expert", "pipeline", "sequence", "tensor")


@dataclass(frozen=True)
class MeshConfig:
    """Sizes for each parallelism axis; -1 on `data` means "the rest"."""

    data: int = -1
    fsdp: int = 1
    expert: int = 1
    pipeline: int = 1
    sequence: int = 1
    tensor: int = 1

    def axis_sizes(self, n_devices: int) -> Dict[str, int]:
        sizes = {"data": self.data, "fsdp": self.fsdp, "expert": self.expert,
                 "pipeline": self.pipeline, "sequence": self.sequence,
                 "tensor": self.tensor}
        fixed = math.prod(v for v in sizes.values() if v > 0)
        n_auto = sum(1 for v in sizes.values() if v <= 0)
        if n_auto > 1:
            raise ValueError("at most one axis may be -1")
        if n_auto == 1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            auto = n_devices // fixed
            sizes = {k: (auto if v <= 0 else v) for k, v in sizes.items()}
        total = math.prod(sizes.values())
        if total > n_devices:
            raise ValueError(
                f"mesh axes {sizes} need {total} devices, have {n_devices}")
        return sizes

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> "MeshConfig":
        unknown = set(d) - set(AXIS_ORDER)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}")
        return cls(**{k: d[k] for k in AXIS_ORDER if k in d})


def build_mesh(config: Optional[MeshConfig] = None,
               devices: Optional[Sequence] = None,
               axis_sizes: Optional[Dict[str, int]] = None):
    """Build a Mesh with the canonical axis order.

    Axes of size 1 are kept (harmless; PartitionSpecs may reference them
    uniformly), so one strategy's specs work on any mesh shape.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axis_sizes is None:
        config = config or MeshConfig()
        axis_sizes = config.axis_sizes(n)
    import numpy as np
    shape = tuple(axis_sizes[a] for a in AXIS_ORDER)
    # A config whose axis product is smaller than the device count uses the
    # first prod(shape) devices (e.g. a pipeline=4 experiment on an
    # 8-device host). Warn: silent under-subscription would hide a 4x
    # throughput loss from a mis-sized axis.
    used = math.prod(shape)
    if used < n:
        import logging
        logging.getLogger(__name__).warning(
            "mesh axes %s use %d of %d devices; the rest are idle",
            dict(axis_sizes), used, n)
    dev_array = np.asarray(devices[:used]).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def fake_mesh(n_devices: int = 8, **axis_sizes):
    """CPU mesh with virtual devices for tests/CI (the `_fake_gpus` analogue).

    Must be called before any other JAX backend initialization in the
    process; see tests/conftest.py.
    """
    import jax
    cpus = [d for d in jax.devices() if d.platform == "cpu"]
    if len(cpus) < n_devices:
        raise RuntimeError(
            f"need {n_devices} CPU devices; set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n_devices} before "
            f"importing jax")
    cfg = MeshConfig(**axis_sizes) if axis_sizes else None
    return build_mesh(cfg, cpus[:n_devices])


# ---------------------------------------------------------------------------
# Slice topology (scheduler-facing; no jax import needed)
# ---------------------------------------------------------------------------

# chips per host for each generation (reference tpu.py:37 consts).
CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5e": 8, "v5p": 4, "v6e": 8}

# Node label under which a host advertises its slice fault domain. All
# hosts of one ICI domain share the value; the GCS groups them into one
# gang for drain/recovery (a preempted host kills the whole slice).
SLICE_LABEL = "ray_tpu.io/slice"
# Node label for the DCN locality domain (pod / cloud zone): slices in
# one zone talk over the same data-center network fabric, so replacement
# domains in the SAME zone are preferred when a gang (or a compiled DAG
# pinned to it) migrates off a preempted slice.
ZONE_LABEL = "ray_tpu.io/zone"
# Real accelerator-type strings use pod aliases (v5e-16 => "v5litepod-16").
GEN_ALIASES = {"v5litepod": "v5e", "v6litepod": "v6e"}


@dataclass
class SliceInfo:
    name: str                 # e.g. "v4-16" or "" for single host
    generation: str = ""      # v4 / v5e / ...
    num_chips: int = 0        # chips in the whole slice
    num_hosts: int = 1
    chips_per_host: int = 4
    worker_id: int = 0        # this host's index within the slice
    topology: str = ""        # e.g. "2x2x2"

    def head_resource(self) -> str:
        """Resource that exists only on host 0 of the slice, used to
        gang-schedule one coordinator per slice (reference
        tpu.py `TPU-<type>-head` pattern)."""
        return f"TPU-{self.name}-head" if self.name else "TPU-head"


def get_slice_info() -> SliceInfo:
    """Detect the TPU slice this host belongs to from standard TPU env vars
    (set on TPU VMs by the runtime; reference reads GCE metadata the same
    way, tpu.py:52)."""
    accel_type = os.environ.get("TPU_ACCELERATOR_TYPE", "")  # e.g. v4-16
    worker_id = int(os.environ.get("TPU_WORKER_ID", "0") or 0)
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    topology = os.environ.get("TPU_TOPOLOGY", "")
    gen = accel_type.split("-")[0] if accel_type else \
        os.environ.get("PALLAS_AXON_TPU_GEN", "")
    gen = GEN_ALIASES.get(gen, gen)
    cph = CHIPS_PER_HOST.get(gen, 4)
    if accel_type:
        try:
            total = int(accel_type.split("-")[1])
        except (IndexError, ValueError):
            total = cph
        # v2/v3 accelerator counts are cores (2/chip); v4+ are chips.
        chips = total // 2 if gen in ("v2", "v3") else total
        hosts = max(1, len(hostnames.split(","))) if hostnames \
            else max(1, chips // cph)
        return SliceInfo(name=accel_type, generation=gen, num_chips=chips,
                         num_hosts=hosts, chips_per_host=cph,
                         worker_id=worker_id, topology=topology)
    return SliceInfo(name="", generation=gen, chips_per_host=cph,
                     worker_id=worker_id, topology=topology)


def detect_slice_id(labels: Optional[Dict[str, str]] = None) -> str:
    """Fault-domain key for this host — unique PER SLICE, shared by every
    host of one ICI domain, "" when the host is not part of a gang.

    Precedence: an explicit `ray_tpu.io/slice` label (tests,
    heterogeneous deployments), then the TPU resource name from the
    runtime (`TPU_NAME`, suffixed with `MEGASCALE_SLICE_ID` so each slice
    of a multislice job is its own domain), then a fingerprint of
    `TPU_WORKER_HOSTNAMES` (identical on every host of one slice,
    distinct across slices). The accelerator type alone
    (`SliceInfo.name`, e.g. "v4-16") is deliberately NOT a fallback: two
    independent slices of the same type would merge into one fault
    domain and a single-host preemption would gang-drain both."""
    explicit = (labels or {}).get(SLICE_LABEL, "")
    if explicit:
        return explicit
    tpu_name = os.environ.get("TPU_NAME", "")
    ms_slice = os.environ.get("MEGASCALE_SLICE_ID", "")
    if tpu_name:
        return f"{tpu_name}/{ms_slice}" if ms_slice else tpu_name
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if hostnames and "," in hostnames:
        import hashlib
        digest = hashlib.sha1(hostnames.encode()).hexdigest()[:12]
        return f"hosts:{digest}"
    return ""


def detect_zone(labels: Optional[Dict[str, str]] = None) -> str:
    """DCN locality key for this host — shared by every slice in one
    pod/zone, "" when unknown. Precedence: an explicit
    `ray_tpu.io/zone` label (tests, heterogeneous deployments), then
    the cloud runtime's zone env (`RAY_TPU_ZONE`, `CLOUD_ZONE`,
    `TPU_ZONE`). Multi-slice DCN topology awareness: gang recovery and
    compiled-DAG migration prefer replacement domains in the SAME zone,
    so cross-slice traffic stays on the local fabric."""
    explicit = (labels or {}).get(ZONE_LABEL, "")
    if explicit:
        return explicit
    for env in ("RAY_TPU_ZONE", "CLOUD_ZONE", "TPU_ZONE"):
        v = os.environ.get(env, "")
        if v:
            return v
    return ""


def slice_bundles(slice_info: SliceInfo) -> List[Dict[str, float]]:
    """Placement-group bundles that gang-reserve a whole slice: one bundle
    per host, chips_per_host TPU each; bundle 0 additionally carries the
    slice-head resource (reference: BackendExecutor's TPU pod scheduling)."""
    per_host = float(min(slice_info.chips_per_host,
                         slice_info.num_chips or slice_info.chips_per_host))
    bundles = []
    for i in range(slice_info.num_hosts):
        b = {"TPU": per_host}
        if i == 0:
            b[slice_info.head_resource()] = 1.0
        bundles.append(b)
    return bundles
