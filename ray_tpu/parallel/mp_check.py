"""Multi-process (multi-controller) gang correctness check.

The real multi-host path: each host runs ONE process owning its local
chips; `jax.distributed.initialize` joins them into one JAX runtime whose
global device list spans every process, and the SAME pjit-compiled SPMD
program runs in lockstep on all of them (collectives ride ICI/DCN — on
CPU test gangs, gloo). Reference analogue: torch DDP process-group
bootstrap in `python/ray/train/torch/config.py:64` +
`train/_internal/backend_executor.py:347` rank mapping; here the gang is
a JAX multi-controller mesh instead of a NCCL process group.

This module provides one FIXED dp x fsdp GPT train-step workload so that
 a) a single-process run over N devices, and
 b) an n-process gang with N/n local devices each
provably compute the SAME loss — numerical equivalence of the sharded
multi-controller step, asserted in CI (tests/test_train.py) and in the
driver-visible `__graft_entry__.dryrun_multichip`.
"""

from __future__ import annotations

import os
import re
import socket
import subprocess
import sys
from typing import List, Optional

# Fixed workload: deterministic config + data seed shared by every mode.
_VOCAB, _SEQ, _BATCH, _STEPS = 512, 64, 8, 2
_DATA_SEED = 7


def step_loss(data_axis: int, fsdp_axis: int) -> float:
    """Run the fixed dp x fsdp workload on the CURRENT jax runtime
    (single- or multi-process alike) and return the step-_STEPS loss.

    In a multi-process gang every process must call this with the same
    arguments; the returned loss is fully replicated, so each process
    reads the identical value from its local shard.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding

    from ray_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
    from ray_tpu.parallel.mesh import MeshConfig, build_mesh
    from ray_tpu.parallel.sharding import strategy_from_name
    from ray_tpu.train.train_step import init_train_state, make_train_step

    cfg = GPTConfig(vocab_size=_VOCAB, d_model=128, n_layers=2, n_heads=4,
                    d_ff=256, max_seq=_SEQ)
    mesh = build_mesh(MeshConfig(data=data_axis, fsdp=fsdp_axis))
    opt = optax.adamw(1e-3)
    strategy = strategy_from_name("fsdp")
    state = init_train_state(lambda: gpt_init(jax.random.PRNGKey(0), cfg),
                             opt, mesh, strategy)
    step = make_train_step(lambda p, b: gpt_loss(p, b, cfg), opt, mesh,
                           strategy, sample_params=state.params)
    tokens_np = np.random.RandomState(_DATA_SEED).randint(
        0, cfg.vocab_size, (_BATCH, _SEQ + 1))
    # device_put against the GLOBAL sharding: each process materializes
    # only its addressable shards of the (identical) host array.
    tokens = jax.device_put(jnp.array(tokens_np, jnp.int32),
                            NamedSharding(mesh, strategy.batch_spec))
    m = None
    for _ in range(_STEPS):
        state, m = step(state, {"tokens": tokens})
    return float(np.asarray(jax.device_get(m["loss"])))


def init_process(rank: int, num_processes: int, coordinator: str,
                 local_devices: int, platform: str = "cpu") -> None:
    """Join this process to the gang. MUST run before any other jax use
    in the process (the platform/device-count flags bind at backend
    init). On CPU gangs the cross-process collective backend is gloo."""
    if platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       flags)
        os.environ["XLA_FLAGS"] = (
            flags +
            f" --xla_force_host_platform_device_count={local_devices}")
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    if num_processes > 1:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=rank)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_gang_subprocesses(n_processes: int, local_devices: int,
                          data_axis: int, fsdp_axis: int,
                          timeout: float = 420.0) -> List[float]:
    """Spawn n worker processes, each `local_devices` CPU devices, run the
    fixed workload over the global mesh; return every process's loss."""
    port = free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # each worker sets its own device count
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/ray_tpu_jax_cache_cpu")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.parallel.mp_check",
             str(rank), str(n_processes), f"127.0.0.1:{port}",
             str(local_devices), str(data_axis), str(fsdp_axis)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for rank in range(n_processes)
    ]
    import time as _time
    losses: List[Optional[float]] = [None] * n_processes
    outputs: List[str] = [""] * n_processes
    deadline = _time.monotonic() + timeout
    try:
        # Poll ALL workers: waiting in rank order would wedge on rank 0
        # (blocked in the gang rendezvous) for the full timeout when a
        # LATER rank crashed at startup — and then discard its stderr.
        pending = set(range(n_processes))
        failed = None
        while pending and _time.monotonic() < deadline:
            for rank in list(pending):
                if procs[rank].poll() is None:
                    continue
                out, _ = procs[rank].communicate()
                outputs[rank] = out or ""
                pending.discard(rank)
                for line in outputs[rank].splitlines():
                    mo = re.match(
                        r"MP_CHECK rank=(\d+) loss=([-\d.naninf]+)", line)
                    if mo:
                        losses[rank] = float(mo.group(2))
                if procs[rank].returncode != 0 and losses[rank] is None:
                    failed = rank
            if failed is not None:
                break
            if pending:
                _time.sleep(0.2)
        if failed is not None:
            tail = "\n".join(outputs[failed].strip().splitlines()[-6:])
            raise RuntimeError(
                f"gang worker {failed} failed "
                f"rc={procs[failed].returncode}:\n{tail}")
        if pending:
            raise RuntimeError(
                f"gang workers {sorted(pending)} still running at the "
                f"{timeout:.0f}s deadline (rendezvous hang?)")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    missing = [r for r, x in enumerate(losses) if x is None]
    if missing:
        tails = "\n---\n".join("\n".join(o.strip().splitlines()[-4:])
                               for o in outputs)
        raise RuntimeError(f"gang workers {missing} produced no loss:\n"
                           f"{tails}")
    return [x for x in losses if x is not None]


def main(argv: List[str]) -> None:
    rank, nprocs, coordinator, local_devices, data_axis, fsdp_axis = (
        int(argv[0]), int(argv[1]), argv[2], int(argv[3]), int(argv[4]),
        int(argv[5]))
    init_process(rank, nprocs, coordinator, local_devices)
    loss = step_loss(data_axis, fsdp_axis)
    print(f"MP_CHECK rank={rank} loss={loss:.6f}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])
