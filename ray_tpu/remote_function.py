"""RemoteFunction: the `@remote` task façade.

Reference parity: python/ray/remote_function.py (RemoteFunction :40,
.options() :160, ._remote() :262).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

from ray_tpu._private import worker_api
from ray_tpu._private.common import SchedulingStrategy


def _resolve_scheduling(options: dict) -> SchedulingStrategy:
    strategy = options.get("scheduling_strategy")
    if strategy is None or strategy == "DEFAULT":
        return SchedulingStrategy()
    if strategy == "SPREAD":
        return SchedulingStrategy(kind="SPREAD")
    # Strategy objects from ray_tpu.util.scheduling_strategies
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy, NodeLabelSchedulingStrategy,
        PlacementGroupSchedulingStrategy)
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return SchedulingStrategy(kind="NODE_AFFINITY", node_id=strategy.node_id,
                                  soft=strategy.soft)
    if isinstance(strategy, NodeLabelSchedulingStrategy):
        def norm(c):
            return {k: ([v] if isinstance(v, str) else list(v))
                    for k, v in (c or {}).items()}
        return SchedulingStrategy(kind="NODE_LABEL",
                                  labels_hard=norm(strategy.hard),
                                  labels_soft=norm(strategy.soft))
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        pg = strategy.placement_group
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP", placement_group_id=pg.id,
            bundle_index=strategy.placement_group_bundle_index,
            capture_child_tasks=strategy.placement_group_capture_child_tasks)
    raise ValueError(f"unknown scheduling strategy {strategy!r}")


def _build_task_template(core, fid: str, submit_kwargs: dict):
    """TaskSpecTemplate for a (function, options) call site: the resolved
    invariants of submit_task_threadsafe, pre-stamped once."""
    from ray_tpu._private.common import TaskSpec, TaskSpecTemplate
    mr = submit_kwargs["max_retries"]
    proto = TaskSpec(
        task_id=None, job_id=core.job_id, name=submit_kwargs["name"],
        function_id=fid, args=[],
        num_returns=submit_kwargs["num_returns"],
        resources=submit_kwargs["resources"],
        scheduling=submit_kwargs["scheduling"],
        max_retries=(core.config.task_max_retries_default if mr < 0
                     else mr),
        retry_exceptions=submit_kwargs["retry_exceptions"],
        owner_address=core.address, owner_worker_id=core.worker_id,
    )
    return TaskSpecTemplate(proto,
                            token=(core, worker_api._state.job_runtime_env))


def _resources_from_options(options: dict) -> Dict[str, float]:
    res = dict(options.get("resources") or {})
    num_cpus = options.get("num_cpus")
    num_tpus = options.get("num_tpus")
    if num_tpus is None:
        num_tpus = options.get("num_gpus")  # alias for drop-in compatibility
    res.setdefault("CPU", 1.0 if num_cpus is None else float(num_cpus))
    if num_tpus:
        res["TPU"] = float(num_tpus)
    if options.get("memory"):
        res["memory"] = float(options["memory"])
    return res


class RemoteFunction:
    def __init__(self, func, options: Optional[dict] = None):
        self._function = func
        self._options = options or {}
        self._function_id: Optional[str] = None
        # Spec template for the steady-state `.remote()` fast path: the
        # invariant spec fields of THIS (function, options) pair,
        # pre-resolved once. Keyed off the core worker + job runtime env
        # identities; `.options()` products get their own (fresh) slot, so
        # an option change can never reuse a stale template.
        self._spec_template = None
        self.__name__ = getattr(func, "__name__", "remote_fn")
        self.__doc__ = getattr(func, "__doc__", None)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self.__name__}' cannot be called directly; "
            f"use '{self.__name__}.remote()'.")

    def __getstate__(self):
        # The spec template is process-local (its token holds the live
        # CoreWorker): a RemoteFunction riding a closure/module pickle
        # must drop it — the receiver rebuilds its own on first call.
        d = dict(self.__dict__)
        d["_spec_template"] = None
        return d

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node (reference: dag_node.py bind)."""
        from ray_tpu.dag.dag_node import FunctionNode
        return FunctionNode(self, args, kwargs)

    def options(self, **new_options) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(new_options)
        rf = RemoteFunction(self._function, merged)
        rf._function_id = self._function_id
        return rf

    def _fid(self) -> str:
        if self._function_id is None:
            from ray_tpu._private.serialization import dumps_function
            data = dumps_function(self._function)
            self._function_id = "fn:" + hashlib.sha1(data).hexdigest()
        return self._function_id

    def _ensure_exported(self, core) -> str:
        fid = self._fid()
        if not worker_api._state.exported_functions.get(fid):
            worker_api._call_on_core_loop(
                core, core.export_function(self._function, fid), 30)
            worker_api._state.exported_functions[fid] = True
        return fid

    def remote(self, *args, **kwargs):
        client = worker_api.client_mode()
        if client is not None:
            return client.submit_function(self, args, kwargs, self._options)
        core = worker_api.get_core()
        tmpl = self._spec_template
        if (tmpl is not None and tmpl.token[0] is core
                and tmpl.token[1] is worker_api._state.job_runtime_env
                and not worker_api._on_core_loop(core)):
            # Steady-state fast path: every invariant (options, resources,
            # scheduling, export) was resolved when the template was
            # built; this call stamps only task id + args.
            refs = core.submit_task_templated(tmpl, args, kwargs)
            return refs[0] if tmpl.num_returns == 1 else refs
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        if streaming:
            num_returns = 0
        on_loop = worker_api._on_core_loop(core)
        export = None
        if on_loop:
            # Async-actor context: defer the function export; it is chained
            # before dispatch inside the submission's background task.
            fid = self._fid()
            if not worker_api._state.exported_functions.get(fid):
                export = (self._function, fid)
                worker_api._state.exported_functions[fid] = True
        else:
            fid = self._ensure_exported(core)
        submit_kwargs = dict(
            name=self.__name__,
            num_returns=num_returns,
            resources=_resources_from_options(opts),
            scheduling=_resolve_scheduling(opts),
            max_retries=(0 if streaming
                         else opts.get("max_retries", -1)),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            is_generator=streaming,
            runtime_env=worker_api.resolve_runtime_env(
                opts.get("runtime_env")),
        )
        if (not streaming and not on_loop
                and submit_kwargs["runtime_env"] is None):
            # Cache the invariants for the next call. Tasks with a
            # runtime_env stay on the legacy path (env preparation
            # mutates the spec per submission), as do on-loop
            # submissions (deferred exports).
            self._spec_template = _build_task_template(
                core, fid, submit_kwargs)
        if on_loop:
            refs = core.submit_task_local(fid, args, kwargs, export=export,
                                          **submit_kwargs)
        else:
            # User thread: reserve ids synchronously, dispatch fire-and-forget
            # (no blocking cross-thread round trip per call).
            refs = core.submit_task_threadsafe(fid, args, kwargs,
                                               **submit_kwargs)
        if num_returns == 1 or streaming:
            return refs[0]
        return refs
