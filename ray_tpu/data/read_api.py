"""Dataset creation APIs (reference: python/ray/data/read_api.py)."""

from __future__ import annotations

import builtins
from typing import Any, List, Optional, Union

import numpy as np

from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.datasource import (BinaryDatasource, CSVDatasource,
                                     Datasource, JSONDatasource,
                                     NumpyDatasource, ParquetDatasource,
                                     RangeDatasource, ReadTask,
                                     TextDatasource)
from ray_tpu.data._internal.logical import InputData, Read


def _make_dataset(op):
    from ray_tpu.data.dataset import Dataset
    return Dataset(op)


def read_datasource(datasource: Datasource, *,
                    parallelism: int = -1) -> "Dataset":
    if parallelism <= 0:
        parallelism = DataContext.get_current().read_op_min_num_blocks
    tasks = datasource.get_read_tasks(parallelism)
    return _make_dataset(Read(list(tasks), name=f"Read{datasource.name}"))


def range(n: int, *, parallelism: int = -1) -> "Dataset":
    """Rows {"id": 0..n-1} (reference: ray.data.range)."""
    return read_datasource(RangeDatasource(n), parallelism=parallelism)


def read_tfrecords(paths, *, parallelism: int = -1) -> "Dataset":
    """Raw TFRecord records as {"bytes": ...} rows (reference:
    ray.data.read_tfrecords)."""
    from ray_tpu.data.datasources import TFRecordDatasource
    return read_datasource(TFRecordDatasource(paths),
                           parallelism=parallelism)


def read_webdataset(paths, *, parallelism: int = -1) -> "Dataset":
    """WebDataset tar shards -> one row per sample (reference:
    ray.data.read_webdataset)."""
    from ray_tpu.data.datasources import WebDatasetDatasource
    return read_datasource(WebDatasetDatasource(paths),
                           parallelism=parallelism)


def read_images(paths, *, size=None, mode=None,
                parallelism: int = -1) -> "Dataset":
    from ray_tpu.data.datasources import ImageDatasource
    return read_datasource(ImageDatasource(paths, size=size, mode=mode),
                           parallelism=parallelism)


def read_orc(paths, *, parallelism: int = -1) -> "Dataset":
    from ray_tpu.data.datasources import ORCDatasource
    return read_datasource(ORCDatasource(paths), parallelism=parallelism)


def read_avro(paths, *, parallelism: int = -1) -> "Dataset":
    from ray_tpu.data.datasources import AvroDatasource
    return read_datasource(AvroDatasource(paths), parallelism=parallelism)


def read_sql(sql: str, connection_factory, *,
             parallelism: int = -1) -> "Dataset":
    """DBAPI2 query -> Dataset (reference: ray.data.read_sql)."""
    from ray_tpu.data.datasources import SQLDatasource
    return read_datasource(SQLDatasource(sql, connection_factory),
                           parallelism=parallelism)


def range_tensor(n: int, *, shape: tuple = (1,),
                 parallelism: int = -1) -> "Dataset":
    return read_datasource(RangeDatasource(n, tensor_shape=tuple(shape)),
                           parallelism=parallelism)


def _chunk_bounds(n: int, parallelism: int):
    """(start, count) block boundaries splitting n rows into at most
    `parallelism` near-equal chunks (empty chunks skipped unless n==0,
    where one empty chunk is emitted so the dataset has a block)."""
    if parallelism <= 0:
        parallelism = min(DataContext.get_current().read_op_min_num_blocks,
                          max(1, n))
    base, rem = builtins.divmod(n, parallelism) if n else (0, 0)
    start = 0
    out = []
    for i in builtins.range(parallelism):
        cnt = base + (1 if i < rem else 0)
        if cnt == 0 and n:
            continue
        out.append((start, cnt))
        start += cnt
    return out or [(0, 0)]


def _blocks_from_list(items: List[Any], parallelism: int,
                      columnar: bool) -> "Dataset":
    """Chunk a materialized row list into blocks (shared by
    from_items/from_torch). columnar=True converts dict rows into the
    canonical columnar form."""
    import ray_tpu
    items = list(items)
    refs, metas = [], []
    for start, cnt in _chunk_bounds(len(items), parallelism):
        chunk = items[start:start + cnt]
        if columnar and chunk and isinstance(chunk[0], dict):
            block = {k: np.asarray([r[k] for r in chunk]) for k in chunk[0]}
        else:
            block = list(chunk)
        refs.append(ray_tpu.put(block))
        metas.append(BlockAccessor.for_block(block).get_metadata())
    return _make_dataset(InputData(refs, metas))


def from_items(items: List[Any], *, parallelism: int = -1) -> "Dataset":
    return _blocks_from_list(items, parallelism, columnar=True)


def from_numpy(arr: Union[np.ndarray, List[np.ndarray]],
               column: str = "data") -> "Dataset":
    import ray_tpu
    arrs = arr if isinstance(arr, list) else [arr]
    refs, metas = [], []
    for a in arrs:
        block = {column: np.asarray(a)}
        refs.append(ray_tpu.put(block))
        metas.append(BlockAccessor.for_block(block).get_metadata())
    return _make_dataset(InputData(refs, metas))


def from_arrow(tables) -> "Dataset":
    """Create a Dataset from pyarrow.Table(s), kept as Arrow blocks
    (reference: python/ray/data/read_api.py from_arrow)."""
    import ray_tpu
    if not isinstance(tables, list):
        tables = [tables]
    refs, metas = [], []
    for t in tables:
        refs.append(ray_tpu.put(t))
        metas.append(BlockAccessor.for_block(t).get_metadata())
    return _make_dataset(InputData(refs, metas))


def from_arrow_refs(refs) -> "Dataset":
    import ray_tpu
    if not isinstance(refs, list):
        refs = [refs]
    # Metadata is computed next to each block — never pull the tables
    # into the driver.
    meta_of = ray_tpu.remote(
        lambda b: BlockAccessor.for_block(b).get_metadata())
    metas = ray_tpu.get([meta_of.remote(r) for r in refs])
    return _make_dataset(InputData(list(refs), metas))


def from_torch(dataset, *, parallelism: int = -1) -> "Dataset":
    """Materialize a map-style torch.utils.data.Dataset into rows of
    {"item": sample} (reference: read_api.from_torch). Simple blocks:
    samples are arbitrary objects (tensors, tuples, ...)."""
    items = [{"item": dataset[i]} for i in builtins.range(len(dataset))]
    return _blocks_from_list(items, parallelism, columnar=False)


def from_huggingface(dataset, *, parallelism: int = -1) -> "Dataset":
    """HuggingFace datasets.Dataset -> ray_tpu Dataset, zero-copy: HF
    datasets are Arrow-backed and the table slices become Arrow blocks
    (reference: read_api.from_huggingface)."""
    if getattr(dataset, "_indices", None) is not None:
        # select/shuffle/filter views keep an indices mapping over the
        # ORIGINAL table; materialize it or we'd return the wrong rows.
        dataset = dataset.flatten_indices()
    table = dataset.data.table
    import ray_tpu
    refs, metas = [], []
    for start, cnt in _chunk_bounds(table.num_rows, parallelism):
        block = table.slice(start, cnt)
        refs.append(ray_tpu.put(block))
        metas.append(BlockAccessor.for_block(block).get_metadata())
    return _make_dataset(InputData(refs, metas))


def _df_to_block(df):
    return {c: df[c].to_numpy() for c in df.columns}


def from_pandas_refs(refs) -> "Dataset":
    """ObjectRefs of pandas DataFrames -> Dataset (blocks converted
    columnar next to the data)."""
    import ray_tpu
    if not isinstance(refs, list):
        refs = [refs]
    to_block = ray_tpu.remote(_df_to_block)
    block_refs = [to_block.remote(r) for r in refs]
    meta_of = ray_tpu.remote(
        lambda b: BlockAccessor.for_block(b).get_metadata())
    metas = ray_tpu.get([meta_of.remote(r) for r in block_refs])
    return _make_dataset(InputData(block_refs, metas))


def from_pandas(dfs) -> "Dataset":
    import ray_tpu
    if not isinstance(dfs, list):
        dfs = [dfs]
    refs, metas = [], []
    for df in dfs:
        block = _df_to_block(df)
        refs.append(ray_tpu.put(block))
        metas.append(BlockAccessor.for_block(block).get_metadata())
    return _make_dataset(InputData(refs, metas))


def read_text(paths, *, parallelism: int = -1, **kw) -> "Dataset":
    return read_datasource(TextDatasource(paths, **kw),
                           parallelism=parallelism)


def read_csv(paths, *, parallelism: int = -1) -> "Dataset":
    return read_datasource(CSVDatasource(paths), parallelism=parallelism)


def read_json(paths, *, parallelism: int = -1) -> "Dataset":
    return read_datasource(JSONDatasource(paths), parallelism=parallelism)


def read_numpy(paths, *, parallelism: int = -1) -> "Dataset":
    return read_datasource(NumpyDatasource(paths), parallelism=parallelism)


def read_binary_files(paths, *, parallelism: int = -1) -> "Dataset":
    return read_datasource(BinaryDatasource(paths), parallelism=parallelism)


def read_parquet(paths, *, parallelism: int = -1,
                 arrow_blocks: bool = True) -> "Dataset":
    return read_datasource(ParquetDatasource(paths, arrow_blocks),
                           parallelism=parallelism)
