"""Execution-wide tunables (reference: python/ray/data/context.py)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DataContext:
    """Per-driver dataset execution configuration.

    target_max_block_size: map/read outputs buffer up to this many bytes
    before emitting a block (dynamic block sizing).
    op_concurrency_cap: max in-flight tasks per physical operator; None =
    derive from cluster CPUs at execution time (streaming backpressure).
    max_buffered_blocks: per-operator bound on completed-but-unconsumed
    output blocks — the executor stops dispatching upstream work while a
    downstream queue is full (reference: backpressure_policy/).
    """

    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    read_op_min_num_blocks: int = 8
    op_concurrency_cap: Optional[int] = None
    max_buffered_blocks: int = 16
    eager_free: bool = True
    verbose_stats: bool = False
    extras: dict = field(default_factory=dict)

    _lock = threading.Lock()
    _current: Optional["DataContext"] = None

    @staticmethod
    def get_current() -> "DataContext":
        with DataContext._lock:
            if DataContext._current is None:
                DataContext._current = DataContext()
            return DataContext._current
