"""ray_tpu.data: lazy, streaming, distributed datasets.

Capability parity with Ray Data (reference: python/ray/data/dataset.py:137,
python/ray/data/_internal/execution/streaming_executor.py:55) redesigned for
a TPU-first stack: blocks are columnar numpy batches that device_put cleanly
onto `jax.sharding` meshes, and `iter_jax_batches` / `streaming_split` feed
SPMD training gangs directly.
"""

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.datasource import Datasource, ReadTask
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.read_api import (from_arrow, from_arrow_refs,
                                   from_huggingface, from_items,
                                   from_numpy, from_pandas,
                                   from_pandas_refs, from_torch,
                                   range,
                                   range_tensor, read_avro,
                                   read_binary_files, read_csv, read_images,
                                   read_json, read_numpy, read_orc,
                                   read_parquet, read_sql, read_text,
                                   read_tfrecords, read_webdataset)
from ray_tpu.data.aggregate import (AggregateFn, Count, Max, Mean, Min, Std,
                                    Sum)

__all__ = [
    "Block", "BlockAccessor", "BlockMetadata", "DataContext", "Dataset",
    "Datasource", "ReadTask", "DataIterator",
    "from_arrow", "from_arrow_refs", "from_huggingface", "from_items",
    "from_numpy", "from_pandas", "from_pandas_refs", "from_torch",
    "range", "range_tensor",
    "read_avro", "read_binary_files", "read_csv", "read_images",
    "read_json", "read_numpy", "read_orc", "read_parquet", "read_sql",
    "read_text", "read_tfrecords", "read_webdataset",
    "AggregateFn", "Count", "Max", "Mean", "Min", "Std", "Sum",
]
