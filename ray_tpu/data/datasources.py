"""Extended datasources: TFRecord, WebDataset, images, ORC, SQL, and
gated connectors.

Reference parity: python/ray/data/datasource/ (38 datasources). The
always-available formats here are implemented on the stdlib/pyarrow; the
cloud/warehouse connectors (BigQuery, Mongo, Delta, Iceberg, Hudi, Lance)
are present as GATED classes that raise with instructions when their
client library is absent — the API surface matches, the dependency is the
user's deployment choice (same posture as the reference, whose connectors
import their clients lazily).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from ray_tpu.data.block import Block
from ray_tpu.data.datasource import (Datasource, FileBasedDatasource,
                                     ReadTask)

# ---------------------------------------------------------------------------
# TFRecord (reference: datasource/tfrecords_datasource.py)
# ---------------------------------------------------------------------------

_CRC_TABLE = None


def _crc32c(data: bytes) -> int:
    """Pure-python CRC32-C (Castagnoli), table-driven."""
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


def read_tfrecord_file(path: str) -> Iterable[bytes]:
    """Yield raw records from a TFRecord file (length/crc framing)."""
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,), _len_crc = struct.unpack("<Q", header[:8]), header[8:]
            data = f.read(length)
            f.read(4)  # data crc (validated lazily: framing crc suffices)
            if len(data) < length:
                return
            yield data


def write_tfrecord_file(path: str, records: Iterable[bytes]):
    with open(path, "wb") as f:
        for rec in records:
            length = struct.pack("<Q", len(rec))
            f.write(length)
            f.write(struct.pack("<I", _masked_crc(length)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))


class TFRecordDatasource(FileBasedDatasource):
    """Records come back as {"bytes": ...}; pair with map() + your proto
    parser (the reference's tf.train.Example decode needs tensorflow)."""

    def _read_file(self, path: str) -> Iterable[Block]:
        recs = list(read_tfrecord_file(path))
        return [{"bytes": np.asarray(recs, dtype=object)}]


# ---------------------------------------------------------------------------
# WebDataset (reference: datasource/webdataset_datasource.py)
# ---------------------------------------------------------------------------


class WebDatasetDatasource(FileBasedDatasource):
    """Tar shards of samples: files sharing a basename form one sample,
    keyed by extension ({"__key__": ..., "jpg": bytes, "json": bytes})."""

    def _read_file(self, path: str) -> Iterable[Block]:
        import tarfile
        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(path) as tar:
            for member in tar:
                if not member.isfile():
                    continue
                base, _, ext = member.name.partition(".")
                if base not in samples:
                    samples[base] = {"__key__": base}
                    order.append(base)
                fobj = tar.extractfile(member)
                samples[base][ext] = fobj.read() if fobj else b""
        return [[samples[k] for k in order]]


# ---------------------------------------------------------------------------
# Images (reference: datasource/image_datasource.py)
# ---------------------------------------------------------------------------


class ImageDatasource(FileBasedDatasource):
    def __init__(self, paths, size: Optional[tuple] = None,
                 mode: Optional[str] = None):
        super().__init__(paths)
        self._size = size
        self._mode = mode

    def _read_file(self, path: str) -> Iterable[Block]:
        try:
            from PIL import Image
        except ImportError as e:
            raise ImportError("read_images requires pillow") from e
        img = Image.open(path)
        if self._mode:
            img = img.convert(self._mode)
        if self._size:
            img = img.resize(self._size)
        return [{"image": np.asarray(img)[None, ...],
                 "path": np.asarray([path], dtype=object)}]


# ---------------------------------------------------------------------------
# ORC / Avro via pyarrow (reference: datasource/orc/avro datasources)
# ---------------------------------------------------------------------------


class ORCDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        try:
            from pyarrow import orc
        except ImportError as e:
            raise ImportError("read_orc requires pyarrow with ORC") from e
        table = orc.read_table(path)
        return [{c: table[c].to_numpy(zero_copy_only=False)
                 for c in table.column_names}]


class AvroDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterable[Block]:
        try:
            import fastavro
        except ImportError as e:
            raise ImportError(
                "read_avro requires fastavro (pip install fastavro on your "
                "cluster image)") from e
        with open(path, "rb") as f:
            rows = list(fastavro.reader(f))
        if not rows:
            return [[]]
        keys = rows[0].keys()
        return [{k: np.asarray([r.get(k) for r in rows]) for k in keys}]


# ---------------------------------------------------------------------------
# SQL (reference: datasource/sql_datasource.py — DBAPI2 over a
# connection factory, works out of the box with sqlite3)
# ---------------------------------------------------------------------------


class SQLDatasource(Datasource):
    def __init__(self, sql: str, connection_factory: Callable[[], Any]):
        self._sql = sql
        self._factory = connection_factory

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        sql, factory = self._sql, self._factory

        def read() -> Iterable[Block]:
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(sql)
                names = [d[0] for d in cur.description]
                rows = cur.fetchall()
            finally:
                conn.close()
            if not rows:
                return [[]]
            cols = list(zip(*rows))
            return [{n: np.asarray(c) for n, c in zip(names, cols)}]

        # DBAPI cursors don't split: one task (the reference shards only
        # when given explicit partition bounds).
        return [ReadTask(read)]


# ---------------------------------------------------------------------------
# Gated connectors: API parity, dependency at deploy time
# ---------------------------------------------------------------------------


def _gated(name: str, dep: str):
    class _Gated(Datasource):
        def __init__(self, *a, **kw):
            raise ImportError(
                f"{name} requires {dep}, which is not installed in this "
                f"environment; install it on your cluster image")
    _Gated.__name__ = name
    return _Gated


MongoDatasource = _gated("MongoDatasource", "pymongo")
BigQueryDatasource = _gated("BigQueryDatasource", "google-cloud-bigquery")
DeltaLakeDatasource = _gated("DeltaLakeDatasource", "deltalake")
IcebergDatasource = _gated("IcebergDatasource", "pyiceberg")
HudiDatasource = _gated("HudiDatasource", "hudi")
LanceDatasource = _gated("LanceDatasource", "lance")
ClickHouseDatasource = _gated("ClickHouseDatasource", "clickhouse-connect")
DatabricksDatasource = _gated("DatabricksDatasource",
                              "databricks-sql-connector")
SnowflakeDatasource = _gated("SnowflakeDatasource",
                             "snowflake-connector-python")
