"""Aggregation functions (reference: python/ray/data/aggregate.py)."""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Union


def _getter(on: Optional[Union[str, Callable]]):
    if on is None:
        def get(r):
            if isinstance(r, dict):
                if len(r) == 1:
                    return next(iter(r.values()))
                raise ValueError(
                    f"aggregate over a multi-column row requires on=<column>;"
                    f" columns: {list(r)}")
            return r
        return get
    if callable(on):
        return on
    return lambda r: r[on]


class AggregateFn:
    """init(key) -> acc; accumulate(acc, row) -> acc; merge; finalize."""

    def __init__(self, init, accumulate, merge, finalize=None,
                 name: str = "agg"):
        self.init = init
        self.accumulate = accumulate
        self.merge = merge
        self.finalize = finalize or (lambda a: a)
        self.name = name


class Count(AggregateFn):
    def __init__(self):
        super().__init__(lambda k: 0, lambda a, r: a + 1, lambda a, b: a + b,
                         name="count()")


class Sum(AggregateFn):
    def __init__(self, on=None):
        g = _getter(on)
        super().__init__(lambda k: 0, lambda a, r: a + g(r),
                         lambda a, b: a + b, name=f"sum({on})")


class Min(AggregateFn):
    def __init__(self, on=None):
        g = _getter(on)
        super().__init__(lambda k: None,
                         lambda a, r: g(r) if a is None else min(a, g(r)),
                         lambda a, b: b if a is None else
                         (a if b is None else min(a, b)),
                         name=f"min({on})")


class Max(AggregateFn):
    def __init__(self, on=None):
        g = _getter(on)
        super().__init__(lambda k: None,
                         lambda a, r: g(r) if a is None else max(a, g(r)),
                         lambda a, b: b if a is None else
                         (a if b is None else max(a, b)),
                         name=f"max({on})")


class Mean(AggregateFn):
    def __init__(self, on=None):
        g = _getter(on)
        super().__init__(lambda k: (0.0, 0),
                         lambda a, r: (a[0] + g(r), a[1] + 1),
                         lambda a, b: (a[0] + b[0], a[1] + b[1]),
                         lambda a: a[0] / a[1] if a[1] else float("nan"),
                         name=f"mean({on})")


class Std(AggregateFn):
    """Welford-mergeable variance; ddof=1 to match the reference."""

    def __init__(self, on=None, ddof: int = 1):
        g = _getter(on)

        def acc(a, r):
            m, m2, n = a
            n += 1
            x = g(r)
            d = x - m
            m += d / n
            m2 += d * (x - m)
            return (m, m2, n)

        def merge(a, b):
            m1, s1, n1 = a
            m2, s2, n2 = b
            if n1 == 0:
                return b
            if n2 == 0:
                return a
            d = m2 - m1
            n = n1 + n2
            return (m1 + d * n2 / n, s1 + s2 + d * d * n1 * n2 / n, n)

        def fin(a):
            _m, m2, n = a
            if n - ddof <= 0:
                return float("nan")
            return math.sqrt(m2 / (n - ddof))

        super().__init__(lambda k: (0.0, 0.0, 0), acc, merge, fin,
                         name=f"std({on})")
