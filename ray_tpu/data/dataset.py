"""Dataset: the lazy distributed dataset façade.

Reference parity: python/ray/data/dataset.py:137. Execution is lazy; every
consumption API drives the streaming executor (executor.py). TPU-first
additions: `iter_jax_batches` device-puts batches onto a sharding, and
`streaming_split` feeds SPMD training gangs per-epoch.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Union)

import numpy as np

import ray_tpu
from ray_tpu.data.aggregate import (AggregateFn, Count, Max, Mean, Min, Std,
                                    Sum)
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data._internal.executor import StreamingExecutor
from ray_tpu.data._internal.logical import (AbstractMap, AllToAll, InputData,
                                            Limit, LogicalOperator, MapSpec,
                                            Union as UnionOp, Zip)
from ray_tpu.data._internal import shuffle as _shuffle


@dataclass
class ActorPoolStrategy:
    """compute= strategy for stateful map_batches (reference:
    ActorPoolStrategy). An explicit `size` pins the pool; otherwise it
    starts at min_size and autoscales up to max_size under backlog."""
    size: Optional[int] = None
    min_size: Optional[int] = None
    max_size: Optional[int] = None

    def __post_init__(self):
        if self.size is not None:
            # Explicit size pins the pool: no autoscaling.
            self.max_size = self.size
            return
        self.size = self.min_size if self.min_size is not None else 2
        if self.max_size is None:
            self.max_size = self.size


class Dataset:
    def __init__(self, op: LogicalOperator,
                 context: Optional[DataContext] = None):
        self._op = op
        self._ctx = context or DataContext.get_current()
        self._last_stats = None

    # ------------------------------------------------------------------
    # Transformations (lazy)
    # ------------------------------------------------------------------
    def _map_op(self, name: str, spec: MapSpec, ray_remote_args=None,
                compute=None) -> "Dataset":
        return Dataset(AbstractMap(name, self._op, [spec],
                                   ray_remote_args, compute), self._ctx)

    def map(self, fn: Callable, *, num_cpus: Optional[float] = None,
            **ray_remote_args) -> "Dataset":
        if num_cpus is not None:
            ray_remote_args["num_cpus"] = num_cpus
        return self._map_op("Map", MapSpec("rows", fn), ray_remote_args)

    def map_batches(self, fn: Union[Callable, type], *,
                    batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    compute: Optional[ActorPoolStrategy] = None,
                    fn_constructor_args: tuple = (),
                    num_cpus: Optional[float] = None,
                    num_tpus: Optional[float] = None,
                    **ray_remote_args) -> "Dataset":
        if num_cpus is not None:
            ray_remote_args["num_cpus"] = num_cpus
        if num_tpus is not None:
            ray_remote_args["num_tpus"] = num_tpus
        if isinstance(fn, type) and compute is None:
            compute = ActorPoolStrategy(size=2)
        spec = MapSpec("batches", fn, batch_size=batch_size,
                       batch_format=batch_format,
                       fn_constructor_args=fn_constructor_args)
        return self._map_op("MapBatches", spec, ray_remote_args, compute)

    def filter(self, fn: Callable) -> "Dataset":
        return self._map_op("Filter", MapSpec("filter", fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._map_op("FlatMap", MapSpec("flat", fn))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def add(batch):
            batch = dict(batch)
            batch[name] = np.asarray(fn(batch))
            return batch
        return self._map_op("AddColumn", MapSpec("batches", add))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}
        return self._map_op("DropColumns", MapSpec("batches", drop))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(batch):
            return {k: batch[k] for k in cols}
        return self._map_op("SelectColumns", MapSpec("batches", select))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def rename(batch):
            return {mapping.get(k, k): v for k, v in batch.items()}
        return self._map_op("RenameColumns", MapSpec("batches", rename))

    def limit(self, n: int) -> "Dataset":
        return Dataset(Limit(self._op, n), self._ctx)

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        def bulk(refs, metas):
            return _shuffle.random_shuffle_bulk(refs, metas, seed, num_blocks)
        return Dataset(AllToAll("RandomShuffle", self._op, bulk), self._ctx)

    def randomize_block_order(self, *, seed: Optional[int] = None) -> "Dataset":
        def bulk(refs, metas):
            import random as _r
            rng = _r.Random(seed)
            idx = list(range(len(refs)))
            rng.shuffle(idx)
            return [refs[i] for i in idx], [metas[i] for i in idx]
        return Dataset(AllToAll("RandomizeBlockOrder", self._op, bulk),
                       self._ctx)

    def repartition(self, num_blocks: int) -> "Dataset":
        def bulk(refs, metas):
            return _shuffle.repartition_bulk(refs, metas, num_blocks)
        return Dataset(AllToAll(f"Repartition[{num_blocks}]", self._op, bulk),
                       self._ctx)

    def sort(self, key, descending: bool = False) -> "Dataset":
        def bulk(refs, metas):
            return _shuffle.sort_bulk(refs, metas, key, descending)
        return Dataset(AllToAll("Sort", self._op, bulk), self._ctx)

    def groupby(self, key) -> "GroupedData":
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(UnionOp([self._op] + [o._op for o in others]),
                       self._ctx)

    def zip(self, other: "Dataset") -> "Dataset":
        return Dataset(Zip(self._op, other._op), self._ctx)

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        rng_seed = seed if seed is not None else np.random.randint(2**31)

        def sample(batch):
            import zlib
            n = len(next(iter(batch.values()))) if batch else 0
            # Derive a per-block seed from the block's content so distinct
            # blocks draw independent masks (a fixed seed would repeat the
            # same mask positions in every block).
            h = rng_seed
            for v in batch.values():
                a = np.asarray(v)
                h = zlib.crc32(a[:64].tobytes() if a.dtype != object
                               else repr(a[:8].tolist()).encode(), h)
                break
            rng = np.random.RandomState((h + n) % (2**31))
            mask = rng.random_sample(n) < fraction
            return {k: v[mask] for k, v in batch.items()}
        return self._map_op("RandomSample", MapSpec("batches", sample))

    # ------------------------------------------------------------------
    # Execution / consumption
    # ------------------------------------------------------------------
    def _execute(self) -> Iterator:
        ex = StreamingExecutor(self._op, self._ctx)
        it = ex.execute()
        self._last_stats = ex.stats
        return it

    def materialize(self) -> "Dataset":
        refs, metas = [], []
        for ref, meta in self._execute():
            refs.append(ref)
            metas.append(meta)
        return Dataset(InputData(refs, metas), self._ctx)

    def to_block_refs(self):
        """[(ObjectRef[Block], BlockMetadata)] — executes the plan."""
        return list(self._execute())

    def to_arrow_refs(self):
        """[ObjectRef[pyarrow.Table]] — one per block (reference:
        Dataset.to_arrow_refs). Blocks already in Arrow form pass
        through untouched."""
        out = []
        for ref, _meta in self._execute():
            block = ray_tpu.get(ref)
            acc = BlockAccessor.for_block(block)
            table = acc.to_batch("pyarrow")
            out.append(ref if table is block else ray_tpu.put(table))
        return out

    def to_numpy_refs(self):
        """[ObjectRef[dict[str, ndarray]]] — numpy-columnar form of each
        block, converted next to the data (reference: to_numpy_refs)."""
        conv = ray_tpu.remote(
            lambda b: BlockAccessor.for_block(b).to_batch("numpy"))
        return [conv.remote(ref) for ref, _meta in self._execute()]

    def to_pandas(self):
        """Materialize the whole dataset as one pandas DataFrame."""
        import pandas as pd
        frames = [BlockAccessor.for_block(b).to_batch("pandas")
                  for b in self.iter_blocks()]
        frames = [f for f in frames if len(f)]
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def iter_blocks(self) -> Iterator[Block]:
        for ref, _meta in self._execute():
            yield ray_tpu.get(ref)

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from BlockAccessor.for_block(block).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None
                     ) -> Iterator[Any]:
        from ray_tpu.data.iterator import batch_blocks
        yield from batch_blocks(self.iter_blocks(), batch_size, batch_format,
                                drop_last, local_shuffle_buffer_size,
                                local_shuffle_seed)

    def iter_stream(self, *, batch_size: Optional[int] = 256,
                    batch_format: str = "numpy",
                    max_queue_depth: int = 4,
                    drop_last: bool = False):
        """Streaming batch iterator with bounded host-side prefetch.

        A producer thread executes the plan and re-batches blocks into a
        `BoundedQueue` of depth `max_queue_depth`; `put` blocks when the
        queue is full, so a slow consumer (a learner paying device time
        per step) throttles block fetching instead of letting batches
        pile up on the host (writer-blocks backpressure — the channels
        discipline, host-side). Returns a `StreamingIngest`: iterate it,
        use it as a context manager, or `close()` to cancel mid-stream
        (the producer drains cleanly and drops its block refs).
        """
        from ray_tpu.data._internal.streaming import StreamingIngest
        from ray_tpu.data.iterator import batch_blocks

        def source():
            return batch_blocks(self.iter_blocks(), batch_size,
                                batch_format, drop_last)

        return StreamingIngest(source, depth=max_queue_depth,
                               name="dataset-stream")

    def iter_jax_batches(self, *, batch_size: int,
                         sharding=None, drop_last: bool = True,
                         dtype=None, **kw) -> Iterator[Any]:
        """Batches as jax.Arrays, optionally placed on a NamedSharding.

        TPU-native addition: the host->device transfer happens here, so a
        training loop can consume device-resident batches directly.
        """
        from ray_tpu.data.iterator import jax_batch_stream
        yield from jax_batch_stream(
            self.iter_batches(batch_size=batch_size, drop_last=drop_last,
                              **kw), sharding, dtype)

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        # Fast path: metadata only, no block fetch.
        return sum(meta.num_rows for _ref, meta in self._execute())

    def _agg(self, agg: AggregateFn):
        acc = agg.init(None)
        for block in self.iter_blocks():
            for row in BlockAccessor.for_block(block).iter_rows():
                acc = agg.accumulate(acc, row)
        return agg.finalize(acc)

    def unique(self, column: str) -> List[Any]:
        """Distinct values of a column (reference: Dataset.unique) —
        per-block dedup next to the data, union on the driver."""
        def block_unique(block):
            acc = BlockAccessor.for_block(block)
            batch = acc.to_batch("numpy")
            if column not in batch:
                raise KeyError(f"no column {column!r}; have "
                               f"{sorted(batch)}")
            return set(np.asarray(batch[column]).tolist())

        uniq = ray_tpu.remote(block_unique)
        out: set = set()
        for part in ray_tpu.get([uniq.remote(ref)
                                 for ref, _m in self._execute()]):
            out |= part
        try:
            return sorted(out)
        except TypeError:  # mixed/unorderable types: stable repr order
            return sorted(out, key=repr)

    def sum(self, on=None):
        return self._agg(Sum(on))

    def min(self, on=None):
        return self._agg(Min(on))

    def max(self, on=None):
        return self._agg(Max(on))

    def mean(self, on=None):
        return self._agg(Mean(on))

    def std(self, on=None, ddof: int = 1):
        return self._agg(Std(on, ddof))

    def aggregate(self, *aggs: AggregateFn) -> dict:
        return {a.name: self._agg(a) for a in aggs}

    def schema(self) -> Optional[List[str]]:
        for _ref, meta in self._execute():
            if meta.schema:
                return meta.schema
        return None

    def columns(self) -> Optional[List[str]]:
        return self.schema()

    def num_blocks(self) -> int:
        return len(list(self._execute()))

    def size_bytes(self) -> int:
        return sum(meta.size_bytes for _ref, meta in self._execute())

    def stats(self) -> str:
        if self._last_stats is None:
            return "(dataset not executed yet)"
        return self._last_stats.summary()

    # ------------------------------------------------------------------
    # Splitting
    # ------------------------------------------------------------------
    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        mat = self.materialize()
        op: InputData = mat._op  # type: ignore[assignment]
        refs, metas = op.block_refs, op.metas
        if equal:
            total = sum(m.num_rows for m in metas)
            per = total // n
            refs, metas = _shuffle.repartition_bulk(refs, metas, n)
            # After repartition blocks differ by <=1 row; trim to equal.
            out = []
            for r, m in zip(refs, metas):
                if m.num_rows > per:
                    from ray_tpu.data._internal.executor import _slice_task
                    sl = ray_tpu.remote(_slice_task).options(num_returns=2)
                    r, mref = sl.remote(r, 0, per)
                    m = ray_tpu.get(mref)
                out.append(Dataset(InputData([r], [m]), self._ctx))
            return out
        groups: List[List[int]] = [[] for _ in range(n)]
        loads = [0] * n
        for i, m in enumerate(metas):
            j = loads.index(min(loads))
            groups[j].append(i)
            loads[j] += m.num_rows
        return [Dataset(InputData([refs[i] for i in g],
                                  [metas[i] for i in g]), self._ctx)
                for g in groups]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        mat = self.materialize()
        op: InputData = mat._op  # type: ignore[assignment]
        refs, metas = op.block_refs, op.metas
        from ray_tpu.data._internal.executor import _slice_task
        sl = ray_tpu.remote(_slice_task).options(num_returns=2)
        offsets = [0]
        for m in metas:
            offsets.append(offsets[-1] + m.num_rows)
        total = offsets[-1]
        bounds = [0] + list(indices) + [total]
        out = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            rs, ms = [], []
            for i, r in enumerate(refs):
                blo, bhi = offsets[i], offsets[i + 1]
                s, e = max(lo, blo), min(hi, bhi)
                if s < e:
                    if s == blo and e == bhi:
                        rs.append(r)
                        ms.append(metas[i])
                    else:
                        rr, mref = sl.remote(r, s - blo, e - blo)
                        rs.append(rr)
                        ms.append(ray_tpu.get(mref))
            if not rs:
                blk = []
                rs = [ray_tpu.put(blk)]
                ms = [BlockAccessor.for_block(blk).get_metadata()]
            out.append(Dataset(InputData(rs, ms), self._ctx))
        return out

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        # Materialize once; counting then splitting would execute the whole
        # plan twice.
        mat = ds.materialize()
        total = sum(m.num_rows for m in mat._op.metas)  # type: ignore
        n_test = int(total * test_size)
        train, test = mat.split_at_indices([total - n_test])
        return train, test

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        """n iterators that each see a disjoint shard per epoch.

        Reference parity: Dataset.streaming_split (output_splitter op);
        feeds each SPMD training worker its per-host shard.
        """
        from ray_tpu.data.iterator import StreamSplitDataIterator
        return StreamSplitDataIterator.create(self, n, equal=equal)

    # ------------------------------------------------------------------
    # Writing
    # ------------------------------------------------------------------
    def write_json(self, path: str):
        import json
        import os
        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            rows = list(BlockAccessor.for_block(block).iter_rows())
            with open(os.path.join(path, f"part-{i:05d}.json"), "w") as f:
                for r in rows:
                    f.write(json.dumps(_jsonable(r)) + "\n")

    def write_csv(self, path: str):
        import csv
        import os
        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            acc = BlockAccessor.for_block(block)
            rows = [_jsonable(r) for r in acc.iter_rows()]
            if not rows:
                continue
            with open(os.path.join(path, f"part-{i:05d}.csv"), "w",
                      newline="") as f:
                w = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
                w.writeheader()
                w.writerows(rows)

    def write_tfrecords(self, path: str, column: str = "bytes"):
        """Write raw records in TFRecord framing (reference:
        tfrecords_datasource write path; records are the given column's
        bytes — proto encoding is the caller's choice, matching the
        read side which returns raw record bytes)."""
        import os
        from ray_tpu.data.datasources import write_tfrecord_file
        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            acc = BlockAccessor.for_block(block)
            records = []
            for row in acc.iter_rows():
                rec = row[column] if isinstance(row, dict) else row
                if isinstance(rec, np.ndarray):
                    rec = rec.tobytes()
                elif isinstance(rec, str):
                    rec = rec.encode()
                records.append(bytes(rec))
            write_tfrecord_file(
                os.path.join(path, f"part-{i:05d}.tfrecords"), records)

    def write_parquet(self, path: str):
        import os
        try:
            import pyarrow as pa
            import pyarrow.parquet as pq
        except ImportError as e:
            raise ImportError("write_parquet requires pyarrow") from e
        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            acc = BlockAccessor.for_block(block)
            table = acc.to_batch("pyarrow")
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    def __repr__(self):
        return f"Dataset(plan={self._op!r})"


def _jsonable(row):
    if isinstance(row, dict):
        return {k: _jsonable(v) for k, v in row.items()}
    if isinstance(row, np.generic):
        return row.item()
    if isinstance(row, np.ndarray):
        return row.tolist()
    return row


class GroupedData:
    """Result of Dataset.groupby (reference: grouped_data.py)."""

    def __init__(self, ds: Dataset, key):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        key = self._key

        def bulk(refs, metas):
            return _shuffle.groupby_bulk(refs, metas, key, list(aggs))
        return Dataset(AllToAll("GroupByAggregate", self._ds._op, bulk),
                       self._ds._ctx)

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on=None) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on=None) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on=None) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on=None) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on=None, ddof: int = 1) -> Dataset:
        return self.aggregate(Std(on, ddof))

    def map_groups(self, fn: Callable) -> Dataset:
        key = self._key

        def apply(batch):
            acc = BlockAccessor.for_block(
                batch if isinstance(batch, dict) else list(batch))
            rows = list(acc.iter_rows())
            kf = key if callable(key) else (lambda r: r[key])
            groups: dict = {}
            for r in rows:
                groups.setdefault(kf(r), []).append(r)
            out = []
            for gk in sorted(groups, key=lambda x: (str(type(x)), x)):
                res = fn(groups[gk])
                out.extend(res if isinstance(res, list) else [res])
            if out and isinstance(out[0], dict):
                return {k: np.asarray([r[k] for r in out]) for k in out[0]}
            return out
        # Shuffle so that each key lands wholly in one block first.
        ds = self._ds.sort(key if not callable(key) else key)
        return ds.repartition(1)._map_op("MapGroups",
                                         MapSpec("batches", apply))
